"""Cluster mode: multiprocess nodes on one machine (or many).

Reference: `python/ray/cluster_utils.py:99` — `Cluster` runs N
raylet-equivalents as separate OS processes, which is how the reference
tests multi-node scheduling and failure handling without real machines
(SURVEY.md §4). Here:

- the driver process is the head: it hosts the GCS-style services
  (node table, object directory) and its own LocalBackend;
- `add_node()` spawns `ray_tpu._private.cluster_node` subprocesses that
  register and execute shipped tasks;
- scheduling: local-first pack, spill to the least-loaded remote node
  with capacity (the reference's hybrid policy shape);
- objects stay with their executing node (owner-based directory); gets
  pull node→node.
"""

from __future__ import annotations

import logging
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import critical_path
from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks
from ray_tpu._private import sched_state
from ray_tpu._private import state as state_mod
from ray_tpu._private import tenancy
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.resources import spec_milli
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.task_spec import TaskKind
from ray_tpu.exceptions import ActorDiedError, OwnerDiedError

# Object-plane observability (ray_tpu_object_* in /api/metrics via the
# runtime-metrics fold; node-tagged through the snapshot-shipping
# plane): shm probe outcome, native pull volume/latency, and time spent
# waiting for a bounded pull slot.
_SHM_HITS = _perf_stats.counter("object_shm_hit")
_SHM_MISSES = _perf_stats.counter("object_shm_miss")
_PULL_BYTES = _perf_stats.counter("object_pull_bytes")
_PULL_SECONDS = _perf_stats.latency("object_pull_seconds")
_PULL_SLOT_WAIT = _perf_stats.latency("object_pull_slot_wait_seconds")

# Fault-path observability (ray_tpu_node_deaths_total,
# ray_tpu_node_death_lost_bytes_total, ray_tpu_reconstructions_total
# {outcome}, ray_tpu_actor_restarts_total{outcome} after the runtime-
# metrics fold): every recovery decision leaves a countable trace, so a
# chaos run's "the job completed" comes with "and here is what it cost".
_NODE_DEATHS = _perf_stats.counter("node_deaths")
_NODE_DEATH_LOST_BYTES = _perf_stats.counter("node_death_lost_bytes")

# Lease-cache observability (ray_tpu_sched_* after the runtime-metrics
# fold): a hit is a submission riding an already-granted (job, shape)
# lease with no head scheduling decision; a miss is a fresh grant; a
# spillback is a grant redirected off an overloaded lease target by the
# node's reported backlog signal.
_LEASE_CACHE_HITS = _perf_stats.counter("sched_lease_cache_hit")
_LEASE_CACHE_MISSES = _perf_stats.counter("sched_lease_cache_miss")
_SPILLBACKS = _perf_stats.counter("sched_spillbacks")


def _recon_counter(outcome: str):
    """reconstructions{outcome}: reexecute | from_spill | exhausted."""
    return _perf_stats.counter("reconstructions", {"outcome": outcome})


def _restart_counter(outcome: str):
    """actor_restarts{outcome}: restarted | exhausted | call_replayed |
    call_rejected | call_deduped."""
    return _perf_stats.counter("actor_restarts", {"outcome": outcome})


def fetch_backoff(attempt: int) -> None:
    """Escalating poll interval for object-arrival waits: sub-ms first
    probes (most objects land within a few ms of submission — a flat
    10 ms sleep put a hard floor under every cross-process get), backing
    off for slow producers. Curve knobs:
    ``object_fetch_backoff_base_s`` / ``object_fetch_backoff_cap_s``."""
    time.sleep(min(
        ray_config.object_fetch_backoff_base_s * (1.6 ** min(attempt, 10)),
        ray_config.object_fetch_backoff_cap_s))


def try_shm_fetch(worker, oid) -> bool:
    """Zero-copy read from the node's shared segment, if the object is
    there. Faster and cheaper than any RPC — always tried first."""
    plane = getattr(worker, "shm_plane", None)
    if plane is None:
        return False
    try:
        found, value = plane.get(oid)
    except Exception:
        return False
    if not found:
        _SHM_MISSES.inc()
        return False
    _SHM_HITS.inc()
    worker.memory_store.put(oid, value, shm=True)
    return True


# Bandwidth-aware pull bounding (reference: pull_manager.h:52 — cap
# in-flight pull bytes): at most `object_pull_max_concurrent` wire
# pulls at once; excess callers wait their turn instead of thrashing
# the link with parallel streams that each crawl. Rebuilt when the
# config knob changes (tests, tuning).
_pull_slots_lock = threading.Lock()
_pull_slots: Optional[threading.BoundedSemaphore] = None
_pull_slots_cap = 0


def _wire_pull_slots() -> threading.BoundedSemaphore:
    global _pull_slots, _pull_slots_cap
    cap = max(1, int(ray_config.object_pull_max_concurrent))
    with _pull_slots_lock:
        if _pull_slots is None or _pull_slots_cap != cap:
            _pull_slots = threading.BoundedSemaphore(cap)
            _pull_slots_cap = cap
        return _pull_slots


def pull_via_transfer(worker, plane, oid, host: str, port: int) -> bool:
    """One bounded, range-striped native pull into the local segment,
    then the zero-copy shm read (reference: ObjectManager Pull with
    chunked parallel transfers)."""
    sanitize_hooks.sched_point("objplane.pull")
    try:
        # Bounded wait for a pull slot: a hung peer must degrade the
        # bound, never deadlock the whole object plane (the C layer's
        # per-syscall socket timeout reclaims the slot eventually).
        slots = _wire_pull_slots()
        t0 = time.monotonic()
        acquired = slots.acquire(timeout=30.0)
        _PULL_SLOT_WAIT.record(time.monotonic() - t0)
        t1 = time.monotonic()
        try:
            rc = plane.store.pull_from_striped(
                oid.binary(), host, port,
                streams=max(1, int(ray_config.object_pull_streams)),
                allow_local=getattr(plane, "allow_local_pull", True))
        finally:
            if acquired:
                slots.release()
        if rc not in (0, -5):
            return False
        if rc == 0:
            pull_s = time.monotonic() - t1
            _PULL_SECONDS.record(pull_s)
            _PULL_BYTES.inc(plane.store.object_size(oid.binary()) or 0)
            # Critical-path stage: a pull inside a traced task charges
            # the request; outside one it still reaches the flight ring.
            if critical_path.enabled():
                critical_path.record_stage(
                    critical_path.ambient_trace_id(), "object.pull", pull_s)
        return try_shm_fetch(worker, oid)
    except Exception:
        return False


def try_transfer_fetch(worker, oid, loc_info) -> bool:
    """Chunked native pull from the owner's transfer server into the
    local segment, then zero-copy read — the cross-host object plane
    (reference: ObjectManager Pull, `pull_manager.h:52`). Skipped when
    the owner shares our segment (plain shm read suffices) or the
    object isn't shm-backed."""
    plane = getattr(worker, "shm_plane", None)
    if plane is None or not loc_info:
        return False
    transfer = loc_info.get("transfer")
    if transfer is None or loc_info.get("shm") == plane.name:
        return False
    return pull_via_transfer(worker, plane, oid, transfer[0], transfer[1])


def resolve_descriptor(worker, oid, desc) -> bool:
    """Materialize an object the owner answered with a descriptor for:
    same segment → plain zero-copy read; served cross-segment → striped
    native pull; no plane here → cannot (caller retries the value
    path)."""
    plane = getattr(worker, "shm_plane", None)
    if plane is None:
        return False
    if desc.shm == plane.name:
        return try_shm_fetch(worker, oid)
    if desc.host:
        return pull_via_transfer(worker, plane, oid, desc.host, desc.port)
    return False


def batch_fetch_objects(worker, oids, locate, self_address):
    """Shared batched-pull core (driver fetch dispatcher + node dep
    fetch): local/shm probes per object, ONE ``locate(need)`` call for
    the rest, transfer-plane pull where possible, then one
    ``get_objects_batch`` RPC per owner — whose replies carry
    ``wire.ObjectDescriptor``s for plane-reachable payloads (resolved
    by shm read / native pull) and framed-pickle values only for small
    or plane-less objects. Returns ``(resolved set, failed {oid: exc},
    unresolved list)`` — unresolved objects simply aren't anywhere yet
    (slow producer) and are the caller's to retry.
    """
    from ray_tpu._private import wire

    store = worker.memory_store
    plane = getattr(worker, "shm_plane", None)
    resolved: set = set()
    failed: Dict[Any, Exception] = {}
    unresolved: list = []
    need = []
    for oid in oids:
        if store.contains(oid) or try_shm_fetch(worker, oid):
            resolved.add(oid)
        else:
            need.append(oid)
    if not need:
        return resolved, failed, unresolved
    infos = locate(need)
    by_addr: Dict[tuple, list] = {}
    for oid, info in zip(need, infos):
        if info is not None and tuple(info["address"]) != tuple(self_address):
            if plane is not None and info.get("shm") == plane.name:
                # Owner shares our segment: the pre-locate probe may
                # simply have raced the seal — re-probe before falling
                # back to a payload-copying RPC.
                if try_shm_fetch(worker, oid):
                    resolved.add(oid)
                    continue
            elif try_transfer_fetch(worker, oid, info):
                resolved.add(oid)
                continue
            by_addr.setdefault(tuple(info["address"]), []).append(oid)
        elif store.contains(oid):
            resolved.add(oid)
        else:
            unresolved.append(oid)
    for addr, group in by_addr.items():
        try:
            replies = RpcClient.to(addr).call(
                "get_objects_batch",
                oids=[o.binary() for o in group], timeout=10.0,
                shm=plane.name if plane is not None else None,
                can_pull=plane is not None)
        except Exception as e:
            for oid in group:
                failed[oid] = e
            continue
        for oid, reply in zip(group, replies):
            ok, value, err = reply
            if not ok:
                unresolved.append(oid)
            elif isinstance(value, wire.ObjectDescriptor):
                if resolve_descriptor(worker, oid, value):
                    resolved.add(oid)
                else:
                    unresolved.append(oid)
            else:
                store.put(oid, value, error=err)
                resolved.add(oid)
    return resolved, failed, unresolved


def descriptor_object_read(worker, transfer_addr, get_object, oids,
                           timeout: float = 30.0, shm=None,
                           can_pull: bool = False):
    """Owner-side ``get_objects_batch`` core: resolve every requested
    object under a shared deadline, then answer with an
    ``ObjectDescriptor`` wherever the requester can reach the sealed
    bytes — same segment (zero-copy read) or our transfer server
    (native pull) — and with the framed-pickle value otherwise. An
    object that left the arena (spilled, evicted) but is large enough
    is republished on demand so the descriptor path stays the default.
    """
    from ray_tpu._private import wire
    from ray_tpu._private.rpc import batched_object_read
    from ray_tpu._private.shm_plane import share_value

    out = batched_object_read(get_object, oids, timeout)
    plane = getattr(worker, "shm_plane", None)
    if plane is None:
        return out
    same_seg = shm is not None and shm == plane.name
    served = can_pull and transfer_addr is not None
    if not (same_seg or served):
        return out
    for i, (oid, reply) in enumerate(zip(oids, out)):
        ok, value, err = reply
        if not ok or err is not None:
            continue
        if not plane.store.contains(oid):
            # Left the arena (spilled/evicted) or never crossed the
            # threshold: republish large restored values on demand.
            if value is None or not share_value(worker, ObjectID(oid),
                                                value):
                continue
        size = plane.store.object_size(oid)
        if size is None:
            continue
        host, port = ("", 0) if same_seg else tuple(transfer_addr)
        out[i] = [True, wire.ObjectDescriptor(
            oid=oid, shm=plane.name, host=host, port=int(port),
            size=int(size)), None]
    return out


# Template-cached milli-demand of a spec (shared core with the local
# backend's _spec_milli — resources.spec_milli).
_spec_milli_of = spec_milli


class _NodeRecord:
    def __init__(self, node_id: str, address: Tuple[str, int],
                 resources: Dict[str, float],
                 transfer: Optional[Tuple[str, int]] = None,
                 shm_name: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources = resources
        self.alive = True
        # Object-plane endpoints: the native transfer server serving this
        # node's shm segment, and the segment name (nodes sharing a
        # segment read each other's objects without any transfer).
        self.transfer = tuple(transfer) if transfer else None
        self.shm_name = shm_name
        # Scheduling labels, e.g. {"ici_slice": "slice-0"}.
        self.labels = dict(labels or {})
        # Pushed resource view (reference: ray_syncer RESOURCE_VIEW
        # deltas): refreshed by report_resources; the scheduler reads
        # this instead of pinging the node per submission.
        self.available: Dict[str, float] = dict(resources)
        self.last_report: float = time.monotonic()
        # Queued-not-running task count from the node's last report
        # (reference: raylet backlog reporting) — lease grants and
        # spill decisions prefer shallow queues.
        self.backlog: int = 0
        # Latest physical-stats sample from the node's in-process agent
        # (node_stats.py), carried on resource reports.
        self.stats: Dict[str, Any] = {}
        # Function-ids whose definitions this node has already received
        # (function-distribution cache; see _strip_exported_func).
        self.known_fns: set = set()
        # Interned spec-template ids this node has received: later
        # submissions of the same shape ship as small TaskCall headers.
        # LRU-bounded at HALF the node cache's capacity, so an id still
        # claimed here cannot have been evicted node-side; an id evicted
        # HERE is simply re-shipped on next use.
        from ray_tpu._private.rpc import LruTable

        self.known_templates = LruTable(4096)
        # In-flight ACTOR-CREATION reservations (milli-resources),
        # charged at record_inflight and released when the creation
        # completes or unwinds. The pushed availability view is stale
        # within a report period, which tasks tolerate (an over-placed
        # task queues and runs when the node frees up) but creations do
        # NOT: an actor pins its CPUs for life, so a burst of creations
        # placed against one stale view overcommits a node with work
        # that can never start while other nodes idle. _choose_node
        # subtracts this. Mutations under the head lock (creations are
        # rare next to tasks); racy reads see a momentarily-stale int.
        self.reserved_milli: Dict[str, int] = {}
        # Head-shard epoch this node last converged with: when a shard
        # process is restarted (its open commit window lost), the head
        # bumps its epoch and the node's next report_resources returns
        # False ONCE — the node re-registers and re-reports its actors
        # and owned objects, repopulating the lost window's keys.
        self.shard_epoch = 0

    def reserve(self, milli: Dict[str, int]) -> None:
        sched_state.milli_add(self.reserved_milli, milli)

    def unreserve(self, milli: Dict[str, int]) -> None:
        sched_state.milli_sub(self.reserved_milli, milli)


class _NullServer:
    """Transport stub for a head constructed with ``start_server=False``
    (model-checking / unit harnesses): carries the address identity and
    a no-op shutdown, nothing listens."""

    def __init__(self, address: Tuple[str, int] = ("127.0.0.1", 0)):
        self.address = tuple(address)

    def shutdown(self) -> None:
        pass


class ClusterHead:
    """GCS-equivalent services hosted in the driver process.

    Beyond the node table and object directory this owns the failure
    story: task *lineage* (creating TaskSpec per return object —
    reference: `reference_count.h:61` lineage pinning), the in-flight
    dispatch table, and a proactive health checker (reference:
    `gcs_health_check_manager.h:39`) that marks dead nodes and triggers
    re-execution of lost work.
    """

    def __init__(self, worker, port: int = 0, start_server: bool = True):
        self.worker = worker
        # The head lock guards the cold/cross-keyed tables (node
        # records, pins, borrowers, actor directory). The HOT tables —
        # object directory, in-flight dispatches, lineage — are
        # lock-partitioned ShardedTables keyed by object/task id, so
        # concurrent submit batches and node object reports stop
        # serializing on one lock. Ordering rule: shard locks are LEAF
        # locks — code holding self._lock may call into a sharded
        # table, never the reverse.
        self._lock = threading.Lock()
        shards = ray_config.sched_head_shards
        self.nodes: Dict[str, _NodeRecord] = {}
        self.object_locations = sched_state.ShardedTable(shards)
        # Reported payload sizes alongside locations (same lifecycle):
        # what locality-aware lease placement scores by — the directory
        # knows where the bytes are AND how many they are.
        self.object_sizes = sched_state.ShardedTable(shards)
        self.actor_nodes: Dict[bytes, str] = {}
        # Failure/recovery state. lineage maps each task-return object to
        # its creating spec; inflight maps task_id -> (node_id, spec)
        # until outputs are reported; actor_specs keeps creation specs for
        # restart-on-node-death; the gate owns restart budgets, the
        # ALIVE/RESTARTING/DEAD FSM, and per-call replay-or-reject.
        self.lineage = sched_state.ShardedTable(shards)
        self.inflight = sched_state.ShardedTable(shards)
        self.actor_specs: Dict[bytes, Any] = {}
        from ray_tpu._private.actor_gate import ActorRestartGate

        self.actor_gate = ActorRestartGate()
        # Gate-registered actors whose (restarted) home is the HEAD's
        # local backend: distinguishes "ALIVE with no directory entry
        # because it lives here" from the transient no-location window
        # mid-death-sweep (where calls must park, not fall through to a
        # backend that has never heard of the actor).
        self.actor_local: set = set()
        self._recon_attempts: Dict[bytes, int] = {}
        # Durable spilled copies by object (node-reported): when a node
        # dies, its spilled RTS1 files outlive the process (they sit on
        # the node-local disk this single-host simulation shares — a
        # real deployment needs shared/remote spill storage for this to
        # hold across hosts), so reconstruction restores from spill
        # instead of re-executing the creating task.
        self.object_spill_urls: Dict[bytes, str] = {}
        # Distributed refcount (reference: reference_count.h borrower
        # protocol, adapted to head-owned objects). A driver release is
        # deferred while any node holds a handle (borrowers) or any
        # dispatched-but-unfinished task's args reference the object
        # (task_pins); the actual free runs when the last holder drops.
        self.borrowers: Dict[bytes, set] = {}          # oid -> {node_id}
        self.task_pins: Dict[bytes, set] = {}          # oid -> {task_id}
        self._task_pinned: Dict[bytes, list] = {}      # task_id -> [oid]
        self.driver_released: set = set()
        # Cluster-wide unfulfilled resource demands (task_id -> request):
        # what the autoscaler reads (reference: GCS resource load). With
        # autoscaling_enabled, no-node-fits tasks wait for capacity
        # instead of failing fast.
        self.pending_demands: Dict[bytes, Dict[str, float]] = {}
        self.autoscaling_enabled = False
        # Function definitions exported to the KV (namespace __fn__).
        self.exported_fns: set = set()
        # Placement-group bundle locations: (pg_id_binary, index) ->
        # node_id, or None for the head itself.
        self.pg_bundle_nodes: Dict[Tuple[bytes, int], Optional[str]] = {}
        handlers = {
            "register_node": self._register_node,
            "report_objects": self._report_objects,
            "report_spilled": self._report_spilled,
            "report_resources": self._report_resources,
            "add_borrowers": self._add_borrowers,
            "remove_borrowers": self._remove_borrowers,
            "locate": self._locate,
            "locate2": self._locate2,
            "locate_batch": self._locate_batch,
            "get_object": self._get_object,
            "get_objects_batch": self._get_objects_batch,
            "get_nodes": self._get_nodes,
            "subscribe": self._subscribe,
            # Typed GCS accessor surface (reference gcs_client.h:61):
            # node processes reach the head's tables through
            # _private/gcs_client.GcsClient instead of raw RPC strings.
            "gcs_kv_put": lambda **kw: self.worker.gcs.kv_put(**kw),
            "gcs_kv_get": lambda **kw: self.worker.gcs.kv_get(**kw),
            "gcs_kv_del": lambda **kw: self.worker.gcs.kv_del(**kw),
            "gcs_kv_keys": lambda **kw: self.worker.gcs.kv_keys(**kw),
            "gcs_named_actors":
                lambda **kw: self.worker.gcs.list_named_actors(**kw),
            "gcs_pg_table": self._gcs_pg_table,
            "gcs_events": self._gcs_events,
            "gcs_record_event": self._gcs_record_event,
            # Cross-node actor plumbing: nodes route actor tasks for
            # non-local actors through the head's cluster backend
            # (reference: the owner's direct actor transport reaches any
            # node; here the head is the directory), and resolve named
            # actors from the head's registry.
            "route_task": self._route_task,
            "report_actor": self._report_actor,
            "report_actors": self._report_actors,
            "gcs_named_actor_register": self._named_actor_register,
            "gcs_named_actor_get": self._named_actor_get,
            "gcs_named_actor_remove": self._named_actor_remove,
            # Observability plane: node task-event deltas + metric
            # snapshots land in the head-side aggregator
            # (_private/obs_plane.py — the GcsTaskManager role).
            "obs_report": self._obs_report,
        }
        if start_server:
            self.server = RpcServer(
                handlers, port=port,
                dedupe_methods=frozenset({"gcs_kv_put", "route_task",
                                          "gcs_named_actor_register"}))
        else:
            # Transport-less head (the model checker drives handlers
            # directly): every directory/recovery code path stays real,
            # only the socket server is stubbed.
            self.server = _NullServer()
        # Long-poll pubsub channels (reference: pubsub/publisher.h:302);
        # node lifecycle events publish here.
        from ray_tpu._private.pubsub import Publisher

        self.publisher = Publisher()
        # Cluster-wide observability aggregator: node-shipped task
        # events + per-node metric snapshots (timeline/tracing/state
        # and the dashboard's merged /api/metrics read this).
        from ray_tpu._private.obs_plane import ObsAggregator

        self.obs = ObsAggregator()
        self.transfer_addr: Optional[Tuple[str, int]] = None
        # node_id -> local log path (populated by Cluster.add_node).
        self.node_logs: Dict[str, str] = {}
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        # Multi-process head control plane (head_shards > 1): the hot
        # row tables above stay as this coordinator's in-memory working
        # copy (read paths never pay an RPC), while every mutation ALSO
        # streams — coalesced per shard — to the owning head shard
        # process, which group-commits it into its own sqlite store.
        # Lease grants additionally consult the owning shard as the
        # registration authority (_grant_lease). Default (1) spawns
        # nothing: today's single-process head byte-for-byte.
        self.shard_router = None
        self._shard_epoch = 0
        self._shard_db_dir = ""
        if start_server and ray_config.head_shards > 1:
            import tempfile

            from ray_tpu._private import head_shards as _head_shards

            self._shard_db_dir = ray_config.head_shard_db_dir or \
                tempfile.mkdtemp(prefix="ray_tpu_head_shards_")
            interval = ray_config.head_shard_commit_interval_s
            self.shard_router = _head_shards.ShardRouter(
                ray_config.head_shards, self._shard_db_dir,
                commit_interval_s=interval if interval > 0 else None)
            from ray_tpu._private import health as _health

            _health.register_section_provider(
                "head_shards", self.shard_health)
            _health.register_degraded_provider(
                "head_shards", self._shard_degraded_reasons)

    # -- registration / directory ---------------------------------------

    def _register_node(self, node_id, address, resources,
                       transfer=None, shm_name=None, labels=None):
        sanitize_hooks.sched_point("head.register")
        with self._lock:
            record = _NodeRecord(node_id, address, resources,
                                 transfer, shm_name, labels)
            # A (re-)registration converges with the CURRENT shard
            # epoch: the re-reports that follow it repopulate any
            # restarted shard's lost window, so this node owes no
            # further re-registration for it.
            record.shard_epoch = self._shard_epoch
            self.nodes[node_id] = record
        self.publisher.publish("node_events", {
            "event": "NODE_ADDED", "node_id": node_id,
            "address": tuple(address)})
        from ray_tpu._private.events import record_event

        record_event("node", f"node {node_id} joined",
                     node_id=node_id, resources=dict(resources or {}))
        self._ensure_health_checker()
        return True

    def _report_resources(self, node_id: str, available, total=None,
                          labels=None, stats=None, backlog=None):
        """Pushed resource-view delta (reference: ray_syncer.h:86). Also
        treated as a liveness heartbeat by the health checker, and the
        carrier for per-node agent stats (node_stats.py). Returning
        False tells an unknown (restarted-head) node to re-register."""
        sanitize_hooks.sched_point("head.node_report")
        with self._lock:
            record = self.nodes.get(node_id)
            if record is None:
                return False  # unknown: node should re-register
            if record.shard_epoch != self._shard_epoch:
                # A head shard process was restarted since this node
                # last converged: its open commit window died with it.
                # Ride the existing re-register path — the node will
                # re-register and re-report its actors and owned
                # objects, restoring the lost window's keys on the
                # restarted shard.
                record.shard_epoch = self._shard_epoch
                return False
            record.available = dict(available)
            if backlog is not None:
                record.backlog = int(backlog)
            if total:
                record.resources = dict(total)
            if labels:
                record.labels = dict(labels)
            if stats:
                record.stats = dict(stats)
            record.last_report = time.monotonic()
        return True

    def _subscribe(self, channel: str, subscriber_id: str, cursor: int,
                   timeout: float = 10.0):
        """Long-poll subscription endpoint (reference: long-poll pubsub,
        `pubsub/publisher.h:188-216`)."""
        return self.publisher.poll(channel, subscriber_id, cursor, timeout)

    def _report_objects(self, oids: List[bytes], address, sizes=None):
        frees = []
        finished = []
        addr = tuple(address)
        # FT gap (a) guard: a dying node's last-gasp report must not
        # apply after the death sweep ran — it would re-point the
        # directory at an unreachable address and pop a REPLAYED call's
        # fresh in-flight record (the head would then believe the
        # replay finished while it is still running). The copies it
        # announces died with the node; recovery owns them now.
        if self._addr_dead(addr) and not self._addr_alive(addr):
            return True
        router = self.shard_router
        for i, oid in enumerate(oids):
            self.object_locations[oid] = addr
            if router is not None:
                # Mirror the directory row to its owning shard process
                # (streamed, coalesced per shard; the shard group-
                # commits it — per-shard durability window).
                router.put("objects", oid, addr)
            if sizes is not None and i < len(sizes) and sizes[i]:
                self.object_sizes[oid] = int(sizes[i])
                if router is not None:
                    router.put("sizes", oid, int(sizes[i]))
            # Outputs landed: the producing task is no longer in
            # flight anywhere; its arg pins drop with it.
            tid = ObjectID(oid).task_id().binary()
            entry = self.inflight.pop(tid, None)
            if entry is not None:
                if router is not None:
                    router.delete("inflight", tid)
                finished.append(entry[1])
                if entry[1].kind == TaskKind.ACTOR_TASK:
                    # Exactly-once protocol tap (rayspec): the call's
                    # output REPORT is applied — its effect is now
                    # observable. A second apply for the same task id
                    # is the FT-gap-(a) double execution the
                    # exactly-once register spec flags.
                    sanitize_hooks.spec_op("spec.call.apply", "call",
                                           self, tid)
                    sanitize_hooks.spec_op("spec.call.apply", "ret",
                                           self, (tid, "applied"))
                if entry[1].kind == TaskKind.ACTOR_CREATION:
                    # Constructed: the node's own reports carry the
                    # held CPUs from dispatch on — drop the reservation.
                    self._unreserve_creation(entry[0], entry[1])
            elif sanitize_hooks.spec_taps_active \
                    and addr != tuple(self.server.address):
                # Recorder installed only: a NODE's report for an
                # actor-task output whose in-flight entry is ALREADY
                # gone (popped by a death sweep that replayed the
                # call, or by the other execution's report) is a
                # further application of the same call — exactly the
                # history the exactly-once spec exists to flag.
                # Head-address self-reports are excluded: those are
                # re-advertisements (local-arg publication, spill
                # restore), not executions; failover re-registration
                # lands on a FRESH head whose history starts empty.
                # The lineage row identifies the oid as an actor-call
                # output; the whole probe is gated so the uninstalled
                # hot path pays nothing for it.
                lspec = self.lineage.get(oid)
                if lspec is not None and \
                        getattr(lspec, "kind", None) == \
                        TaskKind.ACTOR_TASK:
                    sanitize_hooks.spec_op("spec.call.apply", "call",
                                           self, tid)
                    sanitize_hooks.spec_op("spec.call.apply", "ret",
                                           self, (tid, "applied"))
            # Lock-free membership prechecks keep the common case (no
            # pins, no reconstruction attempt) off the head lock
            # entirely. Safe: dict membership is GIL-atomic, and both
            # entries are written strictly BEFORE the dispatch whose
            # report this is (pins at record_inflight, the attempt at
            # reconstruct request), so by report time they are visible.
            if oid in self._recon_attempts or tid in self._task_pinned:
                with self._lock:
                    self._recon_attempts.pop(oid, None)
                    frees.extend(self._unpin_task_locked(tid))
        self._quota_release(finished)
        self._fan_out_frees(frees)
        # Wake the driver's fetch dispatcher for anything it awaits.
        notify = getattr(self.worker, "_fetch_notify", None)
        if notify is not None:
            notify(oids)
        return True

    def _report_spilled(self, oids, urls, node_id=None):
        """A node spilled objects to durable storage: record the URLs so
        reconstruction can restore from disk instead of re-executing
        when the node later dies. A None/empty url drops the record."""
        with self._lock:
            for oid, url in zip(oids, urls):
                if url:
                    self.object_spill_urls[oid] = url
                else:
                    self.object_spill_urls.pop(oid, None)
        return True

    def note_spilled(self, oid: bytes, url: Optional[str]) -> None:
        """In-process form of report_spilled (the head process's own
        store spills through the same directory)."""
        self._report_spilled([oid], [url])

    # -- dispatch bookkeeping (called by ClusterBackendMixin) -----------

    def record_lineage(self, spec) -> None:
        from ray_tpu._private.task_spec import TaskKind

        # Actor-task outputs are reconstructable iff the call has
        # retry budget (reference semantics: objects created by
        # actor tasks can be re-created when max_task_retries > 0;
        # re-execution routes through the restart gate like any
        # replay). Without budget the output is lost with its node
        # and the caller gets a typed ObjectLostError, never a
        # hang (see mark_node_dead's poison pass). Lineage writes are
        # shard-locked only: the lease submit path stops serializing
        # on the head lock here.
        router = self.shard_router
        if spec.kind in (TaskKind.NORMAL_TASK,
                         TaskKind.ACTOR_CREATION) or \
                (spec.kind == TaskKind.ACTOR_TASK
                 and spec.max_retries != 0):
            for oid in spec.return_ids:
                self.lineage[oid.binary()] = spec
                if router is not None:
                    # Durable lineage EDGE (oid -> creating task id):
                    # specs are code-bearing and stay coordinator-
                    # resident; the edge is what a failed-over head
                    # needs to tell "reconstructable" from "lost"
                    # before node re-reports refill the spec tables.
                    router.put("lineage", oid.binary(),
                               spec.task_id.binary())
        if spec.kind == TaskKind.ACTOR_CREATION:
            with self._lock:
                key = spec.actor_id.binary()
                self.actor_specs[key] = spec
            # Gate registration is idempotent: a restart's resubmitted
            # creation spec never resets a partially-consumed budget.
            # `restarts_used` rides the spec (incremented per restart,
            # shipped with it), so a FRESH gate — a failed-over head
            # whose nodes re-report their actors — seeds the budget
            # with the consumed count instead of resetting it
            # (ROADMAP FT gap c).
            self.actor_gate.register(spec.actor_id.binary(),
                                     getattr(spec, "max_restarts", 0),
                                     used=getattr(spec, "restarts_used",
                                                  0))
            if router is not None:
                # Durable restart budget: a failed-over head seeds a
                # fresh gate with the CONSUMED count (ROADMAP FT gap
                # c) even when the re-reporting node itself is gone.
                router.put("actors", spec.actor_id.binary(),
                           (getattr(spec, "max_restarts", 0),
                            getattr(spec, "restarts_used", 0)))

    def _unreserve_creation(self, node_id: str, spec) -> None:
        record = self.nodes.get(node_id)
        if record is not None:
            with self._lock:
                record.unreserve(_spec_milli_of(spec))

    def record_inflight(self, spec, node_id: str) -> None:
        # All kinds, actor calls included: a node death must *fail* an
        # in-flight actor call (typed ActorDiedError) rather than leave
        # its caller hanging on a never-located return object.
        tid = spec.task_id.binary()
        if sanitize_hooks.spec_taps_active and \
                spec.kind == TaskKind.ACTOR_TASK:
            # Exactly-once protocol tap (rayspec): one dispatch attempt
            # of this call is now in flight. `attempt` distinguishes a
            # replay's re-invocation from the original. Guarded like
            # every per-dispatch tap: uninstalled cost is one flag
            # read, no payload construction.
            sanitize_hooks.spec_op(
                "spec.call.invoke", "call", self,
                (tid, getattr(spec, "attempt", 0)))
            sanitize_hooks.spec_op("spec.call.invoke", "ret", self, tid)
        self.inflight[tid] = (node_id, spec)
        if self.shard_router is not None:
            # Durable in-flight row (tid -> node): what a failed-over
            # head re-derives the QuotaLedger's outstanding charges
            # from, keyed to survive on the owning shard alone.
            self.shard_router.put("inflight", tid, node_id)
        if spec.kind == TaskKind.ACTOR_CREATION:
            # Creation reservation: charge the placement against the
            # head's availability view NOW — the node's next report is
            # up to a report period away, and a creation burst placed
            # against one stale view pins a node with actors that can
            # never start (see _NodeRecord.reserved_milli).
            record = self.nodes.get(node_id)
            if record is not None:
                with self._lock:
                    record.reserve(_spec_milli_of(spec))
        # Pin arg objects for the task's lifetime: a driver release
        # racing the dispatch must not free an argument out from
        # under the executing task. Dep-free submissions (the fan-out
        # common case) skip the head lock entirely.
        deps = spec.nested_dependencies()
        if deps:
            with self._lock:
                pinned = []
                for dep in deps:
                    ob = dep.binary()
                    self.task_pins.setdefault(ob, set()).add(tid)
                    pinned.append(ob)
                self._task_pinned[tid] = pinned

    def clear_inflight(self, spec) -> None:
        tid = spec.task_id.binary()
        entry = self.inflight.pop(tid, None)
        if entry is not None and self.shard_router is not None:
            self.shard_router.delete("inflight", tid)
        if entry is not None and spec.kind == TaskKind.ACTOR_CREATION:
            self._unreserve_creation(entry[0], spec)
        frees = []
        if tid in self._task_pinned:  # GIL-atomic precheck (see report)
            with self._lock:
                frees = self._unpin_task_locked(tid)
        self._quota_release([spec])
        self._fan_out_frees(frees)

    def _quota_release(self, specs) -> None:
        """Release tenancy CPU charges for specs leaving the in-flight
        table (token-guarded: no-ops for unquota'd jobs and for specs
        whose charge a local execution already released). Actor
        CREATIONS are lifetime charges — they release at actor death
        (`release_actor_quota`), never at inflight-clear."""
        if not specs:
            return
        backend = getattr(self.worker, "backend", None)
        ledger = getattr(backend, "quota_ledger", None)
        if ledger is None:
            return
        for spec in specs:
            if spec.kind != TaskKind.ACTOR_CREATION:
                ledger.release_cpu(spec)

    def release_actor_quota(self, actor_id: bytes) -> None:
        """An actor died for real (tombstoned/killed): free its
        creation's lifetime CPU charge."""
        backend = getattr(self.worker, "backend", None)
        ledger = getattr(backend, "quota_ledger", None)
        if ledger is None:
            return
        with self._lock:
            spec = self.actor_specs.get(actor_id)
        if spec is not None:
            ledger.release_cpu(spec)

    def _unpin_task_locked(self, tid: bytes) -> list:
        frees = []
        for ob in self._task_pinned.pop(tid, ()):
            pins = self.task_pins.get(ob)
            if pins is not None:
                pins.discard(tid)
                if not pins:
                    del self.task_pins[ob]
                    frees.extend(self._maybe_free_locked(ob))
        return frees

    def _maybe_free_locked(self, oid: bytes) -> list:
        """If the driver released oid and nothing pins/borrows it any
        longer, free it for real. Returns [(addr, oid)] RPC work to do
        outside the lock."""
        if oid not in self.driver_released:
            return []
        if self.borrowers.get(oid) or self.task_pins.get(oid):
            return []
        self.driver_released.discard(oid)
        self.lineage.pop(oid, None)
        self._recon_attempts.pop(oid, None)
        self.object_spill_urls.pop(oid, None)
        self.object_sizes.pop(oid, None)
        loc = self.object_locations.pop(oid, None)
        if loc is not None and loc != self.server.address:
            return [(loc, oid)]
        return []

    def _fan_out_frees(self, frees: list) -> None:
        by_addr: Dict[Tuple[str, int], List[bytes]] = {}
        for addr, oid in frees:
            by_addr.setdefault(addr, []).append(oid)
        for addr, batch in by_addr.items():
            try:
                RpcClient.to(addr).call("free_objects", oids=batch)
            except Exception:
                pass

    def _add_borrowers(self, oids: List[bytes], node_id: str) -> bool:
        with self._lock:
            for oid in oids:
                self.borrowers.setdefault(oid, set()).add(node_id)
        return True

    def _remove_borrowers(self, oids: List[bytes], node_id: str) -> bool:
        frees = []
        with self._lock:
            for oid in oids:
                holders = self.borrowers.get(oid)
                if holders is not None:
                    holders.discard(node_id)
                    if not holders:
                        del self.borrowers[oid]
                        frees.extend(self._maybe_free_locked(oid))
        self._fan_out_frees(frees)
        return True

    # -- health checking -------------------------------------------------

    def _ensure_health_checker(self):
        from ray_tpu._private.config import ray_config

        with self._lock:
            if self._health_thread is not None or \
                    ray_config.health_check_period_s <= 0:
                return
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True,
                name="ray_tpu-health-check")
            self._health_thread.start()

    def _health_loop(self):
        from ray_tpu._private.config import ray_config

        failures: Dict[str, int] = {}
        while not self._health_stop.wait(ray_config.health_check_period_s):
            self.poll_shards()
            with self._lock:
                records = [n for n in self.nodes.values() if n.alive]
            fresh_window = ray_config.resource_report_period_s * \
                ray_config.resource_report_fresh_periods
            for record in records:
                # A recent pushed resource report doubles as a heartbeat:
                # no need to burn an RPC on it.
                if time.monotonic() - record.last_report < fresh_window:
                    failures[record.node_id] = 0
                    continue
                try:
                    RpcClient.to(record.address).call("ping")
                    failures[record.node_id] = 0
                except Exception:
                    count = failures.get(record.node_id, 0) + 1
                    failures[record.node_id] = count
                    if count >= ray_config.health_check_failure_threshold:
                        self.mark_node_dead(record.node_id,
                                            reason="health check failed")

    def poll_shards(self) -> list:
        """Supervise the head shard processes: restart any crashed one
        from its own durable db (acked rows reload) and bump the shard
        epoch so every node's next report returns False once — the
        re-registration path repopulates the crashed shard's lost
        commit window. Returns the restarted shard indices."""
        router = self.shard_router
        if router is None:
            return []
        restarted = router.poll()
        try:
            self._shard_stats_cache = {row["index"]: row
                                       for row in router.stats()}
            self._fold_shard_commit_stats(self._shard_stats_cache)
        except Exception:
            pass
        if restarted:
            from ray_tpu._private.events import record_event

            with self._lock:
                self._shard_epoch += 1
            record_event(
                "head", f"head shard(s) {restarted} restarted; nodes "
                f"will re-register (epoch {self._shard_epoch})",
                severity="WARNING", shards=list(restarted))
        return restarted

    def _fold_shard_commit_stats(self, cache: dict) -> None:
        """Fold shard-side group-commit progress into the coordinator's
        fast-path stats so runtime_metrics exports
        ``ray_tpu_head_shard_commit_seconds_p50/_p95{shard}``: the
        shard processes keep their own counters, so the supervisor's
        poll records the mean window duration of the commits completed
        since the previous poll."""
        from ray_tpu._private import perf_stats

        last = getattr(self, "_shard_commit_seen", None)
        if last is None:
            last = self._shard_commit_seen = {}
        for index, row in cache.items():
            commits = row.get("commits")
            if commits is None:
                continue
            seen_n, seen_s = last.get(index, (0, 0.0))
            total_s = row.get("commit_seconds_total", 0.0)
            if commits > seen_n:
                perf_stats.latency(
                    "head_shard_commit_seconds",
                    {"shard": str(index)}).record(
                        (total_s - seen_s) / (commits - seen_n))
            last[index] = (commits, total_s)

    def shard_health(self) -> list:
        """Per-shard verdicts for /api/healthz: liveness + streamed
        backlog read locally (the provider contract forbids RPC here),
        merged with the shard-side stats the supervisor's last poll
        cached (rows held, group-commit count/latency)."""
        router = self.shard_router
        if router is None:
            return []
        cache = getattr(self, "_shard_stats_cache", {})
        out = []
        for row in router.local_stats():
            verdict = "ok" if row.get("alive") else "dead"
            if row.get("alive") and row.get("backlog", 0) > 4096:
                verdict = "backlogged"
            merged = {"shard": row.get("index"), "verdict": verdict,
                      "backlog": row.get("backlog", 0)}
            cached = cache.get(row.get("index"))
            if cached:
                merged.update({k: cached[k] for k in
                               ("applied", "rows", "commits",
                                "last_commit_s") if k in cached})
            out.append(merged)
        return out

    def _shard_degraded_reasons(self) -> list:
        return [f"head shard {row['shard']} {row['verdict']}"
                for row in self.shard_health()
                if row["verdict"] != "ok"]

    def stop(self):
        self._health_stop.set()
        if self.shard_router is not None:
            from ray_tpu._private import health as _health

            _health.unregister_section_provider("head_shards")
            _health.unregister_degraded_provider("head_shards")
            self.shard_router.close()
            self.shard_router = None

    # -- node death + recovery -------------------------------------------

    def mark_node_dead(self, node_id: str, reason: str = "") -> None:
        """Purge the dead node from the directory and re-execute what it
        held: in-flight tasks are resubmitted, its actors restarted on
        surviving nodes (within max_restarts), and objects it owned are
        left to on-demand lineage reconstruction (`_maybe_reconstruct`).
        Reference: `gcs_node_manager` death flow + `task_manager.h`
        resubmit + `object_recovery_manager.h:106`.
        """
        with self._lock:
            record = self.nodes.get(node_id)
            if record is None or not record.alive:
                return
            record.alive = False
            addr = record.address
            # Objects whose only copy was there are gone. (Their spill
            # URLs — durable disk copies — survive in
            # object_spill_urls: reconstruction restores from those
            # first.)
            # Sharded-table scans under the head lock are fine (shard
            # locks are leaf locks); per-shard snapshots are consistent
            # enough — a report racing the sweep could always land
            # wholly before or after it.
            lost = [oid for oid, loc in self.object_locations.items()
                    if loc == addr]
            lost_bytes = sum(self.object_sizes.get(oid, 0)
                             for oid in lost)
            router = self.shard_router
            for oid in lost:
                self.object_locations.pop(oid, None)
                self.object_sizes.pop(oid, None)
                if router is not None:
                    router.delete("objects", oid)
                    router.delete("sizes", oid)
            resubmit = [spec for (nid, spec) in self.inflight.values()
                        if nid == node_id]
            for spec in resubmit:
                self.inflight.pop(spec.task_id.binary(), None)
                if router is not None:
                    router.delete("inflight", spec.task_id.binary())
            from ray_tpu._private.events import record_event

            # The death event carries the damage assessment: what the
            # recovery machinery now has to make good on.
            record_event("node", f"node {node_id} marked dead: {reason}",
                         severity="ERROR", node_id=node_id,
                         lost_objects=len(lost),
                         lost_bytes=int(lost_bytes),
                         inflight_tasks=len(resubmit))
            _NODE_DEATHS.inc()
            _NODE_DEATH_LOST_BYTES.inc(int(lost_bytes))
            # A dead node can no longer borrow anything; dropping it may
            # unblock deferred frees (fanned out after the lock).
            dead_frees = []
            for oid in [o for o, holders in self.borrowers.items()
                        if node_id in holders]:
                holders = self.borrowers[oid]
                holders.discard(node_id)
                if not holders:
                    del self.borrowers[oid]
                    dead_frees.extend(self._maybe_free_locked(oid))
            dead_actors = [aid for aid, nid in self.actor_nodes.items()
                           if nid == node_id]
            # Bundles reserved there are gone; tasks targeting them fail
            # with PlacementGroupSchedulingError until re-reserved.
            for key, nid in list(self.pg_bundle_nodes.items()):
                if nid == node_id:
                    del self.pg_bundle_nodes[key]
        logging.getLogger(__name__).warning(
            "node %s marked dead (%s): %d objects lost, %d tasks in "
            "flight, %d actors", node_id, reason, len(lost),
            len(resubmit), len(dead_actors))
        # Unrecoverable losses fail FAST: a lost object with no lineage
        # (e.g. a zero-retry actor call's output) and no durable spill
        # copy can never be produced again — a waiting get must raise a
        # typed ObjectLostError, not hang out its deadline. put() is a
        # no-op on entries the driver already resolved.
        from ray_tpu.exceptions import ObjectLostError

        with self._lock:
            unrecoverable = [
                oid for oid in lost
                if oid not in self.lineage
                and oid not in self.object_spill_urls]
        for oid in unrecoverable:
            if not self.worker.memory_store.contains(ObjectID(oid)):
                self.worker.memory_store.put(
                    ObjectID(oid), None, error=ObjectLostError(
                        oid.hex()[:12],
                        f"object {oid.hex()[:12]} was lost when node "
                        f"{node_id} died and has no lineage or spilled "
                        f"copy to recover from"))
        self.publisher.publish("node_events", {
            "event": "NODE_DEAD", "node_id": node_id, "reason": reason})
        # A dead node stops scraping-by-proxy: drop its metric snapshot
        # so the merged exposition doesn't freeze its last values
        # forever (its task events stay — history outlives the node).
        self.obs.forget_node(node_id)
        self._fan_out_frees(dead_frees)
        # An actor whose CREATION was still in flight on the dead node
        # is not restarting — it never finished constructing. The
        # resubmit loop re-drives the creation under the spec's own
        # max_retries; routing it through _restart_actor too would
        # double-submit the creation AND burn restart budget on a
        # first attempt.
        inflight_creations = {
            spec.actor_id.binary() for spec in resubmit
            if spec.kind == TaskKind.ACTOR_CREATION}
        # Restart actors first so resubmitted / queued actor tasks find a
        # live location.
        for aid in dead_actors:
            if aid in inflight_creations:
                with self._lock:
                    self.actor_nodes.pop(aid, None)
                continue
            self._restart_actor(aid, node_id)
        # Dead-node tasks left the in-flight table: release their
        # tenancy CPU charges BEFORE the resubmit re-enters admission
        # (a replay must re-acquire like any dispatch, not double-hold).
        # _quota_release itself keeps creations' lifetime charges held
        # through the restart, and actor-task releases are token-
        # guarded no-ops.
        self._quota_release(resubmit)
        for spec in resubmit:
            if spec.kind == TaskKind.ACTOR_TASK:
                # Replay-or-reject (reference: max_task_retries covers
                # system failures): a call with retry budget replays
                # against the restarted actor; one without rejects with
                # an error naming the restart state and budgets.
                self.recover_actor_call(spec)
                continue
            self._resubmit_lost_task(spec, node_id)

    def _restart_actor(self, actor_id: bytes, dead_node: str) -> None:
        with self._lock:
            spec = self.actor_specs.get(actor_id)
            self.actor_nodes.pop(actor_id, None)
        reason = f"its node {dead_node} died"
        if spec is None:
            self.actor_gate.mark_dead(
                actor_id, reason + " and no creation spec is recorded")
            return
        if not self.actor_gate.begin_restart(actor_id, reason):
            # Budget exhausted: tombstoned by the gate — later calls
            # fail FAST with the cause, instead of falling through to a
            # backend that has never heard of the actor. The dead
            # actor's lifetime CPU charge frees with it.
            _restart_counter("exhausted").inc()
            self.release_actor_quota(actor_id)
            return
        _restart_counter("restarted").inc()
        # The consumed-restart count travels ON the spec: the node
        # hosting the replacement re-reports it on head failover, so a
        # fresh gate never resets a partially-spent budget.
        spec.restarts_used = getattr(spec, "restarts_used", 0) + 1
        # Re-run the creation spec through the normal scheduler; it
        # re-registers the actor's node on dispatch (set_actor_node →
        # gate.ready releases parked callers).
        self._resubmit(spec)

    def set_actor_node(self, actor_id: bytes, node_id: str) -> None:
        """The ONE place an actor gains a live location: directory entry
        plus the gate's RESTARTING→ALIVE edge (parked calls dispatch)."""
        with self._lock:
            self.actor_nodes[actor_id] = node_id
            self.actor_local.discard(actor_id)
        self.actor_gate.ready(actor_id)

    def recover_actor_call(self, spec) -> None:
        """An actor call that was in flight on (or failed to reach) a
        dead node: gate-decided replay-or-reject.

        Caller-side dedupe on return-object identity first (ROADMAP FT
        gap a): the death sweep's in-flight snapshot races the call's
        output REPORT — a call whose output was already applied by the
        time we decide here EXECUTED; replaying it would run its
        effects twice and burn its retry budget on a success. "Applied"
        is judged by the call's own return objects: already resolved in
        the caller's store, located on a surviving node, or durably
        spilled. An output genuinely lost with the node (none of the
        above) still replays — that residual window is the documented
        at-least-once slice reference semantics share."""
        if self._call_output_applied(spec):
            _restart_counter("call_deduped").inc()
            with self._lock:
                frees = self._unpin_task_locked(spec.task_id.binary())
            self._fan_out_frees(frees)
            return

        def resubmit(s):
            _restart_counter("call_replayed").inc()
            self._resubmit(s)

        def fail(s, msg, dead):
            _restart_counter("call_rejected").inc()
            self._fail_actor_call(s, msg, dead)

        self.actor_gate.recover_call(spec, resubmit, fail)

    def _call_output_applied(self, spec) -> bool:
        """Every return object of the call is already obtainable — the
        dedupe predicate for replay decisions (see
        recover_actor_call)."""
        if not spec.return_ids:
            return False
        for oid in spec.return_ids:
            ob = oid.binary()
            if self.worker.memory_store.contains(oid):
                continue
            if ob in self.object_spill_urls:
                continue
            loc = self.object_locations.get(ob)
            if loc is not None and self._addr_alive(loc):
                continue
            return False
        return True

    def _addr_alive(self, addr) -> bool:
        addr = tuple(addr)
        with self._lock:
            return any(record.alive and record.address == addr
                       for record in self.nodes.values())

    def _addr_dead(self, addr) -> bool:
        """The address belongs to a node marked dead (an UNKNOWN
        address answers False: in-process self-reports have no node
        record and must keep flowing)."""
        addr = tuple(addr)
        with self._lock:
            return any(not record.alive and record.address == addr
                       for record in self.nodes.values())

    def _fail_actor_call(self, spec, msg: str, dead: bool) -> None:
        from ray_tpu.exceptions import ActorDiedError, \
            ActorUnavailableError

        err = ActorDiedError(spec.actor_id.hex()[:8], msg) if dead \
            else ActorUnavailableError(msg)
        for oid in spec.return_ids:
            self.worker.memory_store.put(oid, None, error=err)
        with self._lock:
            frees = self._unpin_task_locked(spec.task_id.binary())
        self._fan_out_frees(frees)

    def _resubmit_lost_task(self, spec, node_id: str) -> None:
        """Node-death resubmit with per-spec retry accounting
        (reference: max_retries covers worker/node failures): each
        death consumes one unit of the spec's own budget — and rides
        the wire on the resubmitted TaskCall — instead of resubmitting
        unconditionally forever."""
        from ray_tpu import exceptions as exc

        if spec.max_retries == 0:
            attempts = getattr(spec, "attempt", 0)
            for oid in spec.return_ids:
                self.worker.memory_store.put(
                    oid, None, error=exc.TaskError(
                        exc.WorkerCrashedError(
                            f"node {node_id} died with the task in "
                            f"flight and its retry budget is exhausted "
                            f"(attempt {attempts + 1}, 0 retries left)"),
                        spec.describe()))
            with self._lock:
                frees = self._unpin_task_locked(spec.task_id.binary())
            self._fan_out_frees(frees)
            return
        if spec.max_retries > 0:
            spec.max_retries -= 1
        spec.attempt = getattr(spec, "attempt", 0) + 1
        self._resubmit(spec)

    def _resubmit(self, spec) -> None:
        try:
            self.worker.backend.submit(spec)
        except Exception as e:  # pragma: no cover - best effort
            from ray_tpu import exceptions as exc

            for oid in spec.return_ids:
                self.worker.memory_store.put(
                    oid, None, error=exc.TaskError(e, spec.describe()))
            # The task will never complete: drop its arg pins or any
            # driver-released arg stays pinned (and unfreed) forever.
            with self._lock:
                frees = self._unpin_task_locked(spec.task_id.binary())
            self._fan_out_frees(frees)

    def release_objects(self, oids: List[bytes]) -> None:
        """Driver refcount hit zero. Objects still borrowed by a node or
        pinned by an in-flight task's args defer their free until the
        last holder drops (reference: ReferenceCounter borrower
        protocol); the rest free immediately."""
        frees = []
        with self._lock:
            for oid in oids:
                self.driver_released.add(oid)
                frees.extend(self._maybe_free_locked(oid))
        self._fan_out_frees(frees)

    def unrelease_objects(self, oids: List[bytes]) -> None:
        """The driver re-acquired a handle (e.g. an actor returned a
        borrowed ref back): a pending deferred release must not fire
        when the last borrower later drops."""
        with self._lock:
            for oid in oids:
                self.driver_released.discard(oid)

    def _maybe_reconstruct(self, oid: bytes, _chain=None) -> None:
        """On-demand lineage reconstruction: a requested object with no
        live copy restores from its durable spilled copy when one is
        known, else re-executes its creating task — and does so
        TRANSITIVELY: a re-executed task whose own arguments were also
        lost reconstructs them first (depth/cycle-guarded; each object
        charged its own max_reconstruction_attempts)."""
        from ray_tpu._private.config import ray_config

        if not ray_config.enable_object_reconstruction:
            return
        with self._lock:
            spec = self.lineage.get(oid)
            spill_url = self.object_spill_urls.get(oid)
            # A durable spilled copy is recoverable WITHOUT lineage
            # (e.g. a zero-retry actor call's spilled output), so the
            # spill check must not sit behind the lineage requirement.
            if spec is None and spill_url is None:
                return
            if spec is not None and \
                    spec.task_id.binary() in self.inflight:
                return  # already being re-executed
            attempts = self._recon_attempts.get(oid, 0)
            if attempts >= ray_config.max_reconstruction_attempts:
                _recon_counter("exhausted").inc()
                return
            self._recon_attempts[oid] = attempts + 1
        sanitize_hooks.sched_point("recon.request")
        if spill_url is not None and \
                self._restore_from_spill(oid, spill_url):
            _recon_counter("from_spill").inc()
            return
        if spec is None:
            # The spill copy was the ONLY recovery path and it is gone
            # (stale URL): poison waiting gets now — never a hang.
            from ray_tpu.exceptions import ObjectLostError

            object_id = ObjectID(oid)
            if not self.worker.memory_store.contains(object_id):
                self.worker.memory_store.put(
                    object_id, None, error=ObjectLostError(
                        oid.hex()[:12],
                        f"object {oid.hex()[:12]} has no lineage and "
                        f"its spilled copy could not be restored"))
            return
        # Cycle/depth guard for the recursive walk: a lineage loop (or a
        # pathological chain) terminates; the per-object attempt charge
        # above remains the authoritative bound.
        chain = _chain if _chain is not None else set()
        tid = spec.task_id.binary()
        if tid in chain or \
                len(chain) >= ray_config.max_reconstruction_depth:
            return
        chain = chain | {tid}
        # Transitive: re-executing this spec needs its args resident
        # somewhere — eagerly reconstruct the ones that are lost too,
        # so the re-execution's dep fetch finds (or soon finds) them
        # instead of burning its whole deadline polling.
        for dep in spec.nested_dependencies():
            db = dep.binary()
            with self._lock:
                have = db in self.object_locations
            if not have and not self.worker.memory_store.contains(dep):
                self._maybe_reconstruct(db, chain)
        logging.getLogger(__name__).info(
            "reconstructing object %s via lineage (attempt %d)",
            oid.hex()[:12], attempts + 1)
        _recon_counter("reexecute").inc()
        sanitize_hooks.sched_point("recon.resubmit")
        self._resubmit(spec)

    def _restore_from_spill(self, oid: bytes, url: str) -> bool:
        """Restore a lost object from its durable spilled payload: the
        surviving copy IS the object — no re-execution. The restored
        value republishes through the object plane (share_value) so
        outstanding descriptors and cross-node reads stay valid."""
        sanitize_hooks.sched_point("recon.restore")
        from ray_tpu._private.spilling import restore_spilled_payload

        try:
            value = restore_spilled_payload(url)
        except Exception:
            # Stale URL (file reclaimed, dead node's dir destroyed):
            # drop the record and fall back to re-execution.
            with self._lock:
                self.object_spill_urls.pop(oid, None)
            return False
        object_id = ObjectID(oid)
        self.worker.memory_store.put(object_id, value)
        from ray_tpu._private.shm_plane import share_value

        share_value(self.worker, object_id, value)
        logging.getLogger(__name__).info(
            "restored lost object %s from spilled copy %s",
            oid.hex()[:12], url)
        # The head itself now owns a live copy: advertise it (also
        # wakes the driver's fetch dispatcher for waiting gets).
        self._report_objects([oid], self.server.address)
        return True

    def _locate(self, oid: bytes):
        """Owner's RPC address, or None. (Legacy callers; see _locate2.)"""
        info = self._locate2(oid)
        return info["address"] if info else None

    def _locate2(self, oid: bytes):
        """Rich location: {"address", "transfer", "shm"} of the owner.
        A miss for an object with known lineage kicks off reconstruction
        (the caller keeps polling and picks up the re-executed result)."""
        with self._lock:
            loc = self.object_locations.get(oid)
            if loc is not None:
                for n in self.nodes.values():
                    if n.address == loc:
                        return {"address": loc, "transfer": n.transfer,
                                "shm": n.shm_name}
                if loc == self.server.address:
                    return self._self_location()
                return {"address": loc, "transfer": None, "shm": None}
        if self.worker.memory_store.contains(ObjectID(oid)):
            return self._self_location()
        self._maybe_reconstruct(oid)
        return None

    def _self_location(self):
        plane = getattr(self.worker, "shm_plane", None)
        return {"address": self.server.address,
                "transfer": getattr(self, "transfer_addr", None),
                "shm": plane.name if plane else None}

    def _get_object(self, oid: bytes, timeout: float = 30.0):
        object_id = ObjectID(oid)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, value, error = self.worker.memory_store.peek(object_id)
            if ready:
                return True, value, error
            time.sleep(0.005)
        return False, None, None

    def _locate_batch(self, oids):
        """One RPC locates a whole dependency set (batched arg-fetch:
        the per-arg locate round trips were the forced-remote dispatch
        tax)."""
        return [self._locate2(oid) for oid in oids]

    def _get_objects_batch(self, oids, timeout: float = 30.0,
                           shm=None, can_pull: bool = False):
        return descriptor_object_read(
            self.worker, getattr(self, "transfer_addr", None),
            lambda oid, t: self._get_object(oid, timeout=t), oids,
            timeout, shm=shm, can_pull=can_pull)

    def _route_task(self, spec) -> bool:
        """Submit a node-originated spec through the head's cluster
        backend (which knows where every actor lives); results travel
        back through the object plane like any other output."""
        self.worker.backend.submit(spec)
        return True

    def _report_actor(self, spec, node_id: str,
                      restarts_used: Optional[int] = None) -> bool:
        """An actor created LOCALLY inside a node process registers with
        the head's directory, so handles to it route from anywhere and
        it gets the same restart bookkeeping as head-dispatched actors.
        ``restarts_used`` rides a node's RE-report after head failover:
        the fresh gate must seed the budget with what the actor already
        consumed (head-driven restarts on the spec + node-local worker
        restarts), not reset it (ROADMAP FT gap c)."""
        if restarts_used is not None:
            spec.restarts_used = max(
                getattr(spec, "restarts_used", 0), int(restarts_used))
        self.record_lineage(spec)
        self.set_actor_node(spec.actor_id.binary(), node_id)
        return True

    def _report_actors(self, specs, node_id: str,
                       restarts_used=None) -> bool:
        """Group-committed actor registration: one RPC registers a
        whole node's actors (same record_lineage/restart-gate calls as
        the singular form — semantics unchanged, transport O(batches))."""
        for i, spec in enumerate(specs):
            used = restarts_used[i] if restarts_used is not None \
                and i < len(restarts_used) else None
            self._report_actor(spec, node_id, restarts_used=used)
        return True

    def _named_actor_register(self, name, namespace, handle) -> bool:
        self.worker.gcs.register_named_actor(name, namespace, handle)
        return True

    def _named_actor_get(self, name, namespace):
        return self.worker.gcs.get_named_actor(name, namespace)

    def _named_actor_remove(self, actor_id: bytes) -> bool:
        from ray_tpu._private.ids import ActorID

        self.worker.gcs.remove_named_actor_by_id(ActorID(actor_id))
        return True

    def _obs_report(self, node_id: str, events=None, metrics=None,
                    stages=None):
        return self.obs.report(node_id, events=events, metrics=metrics,
                               stages=stages)

    @staticmethod
    def _gcs_events(limit: int = 200, source=None):
        from ray_tpu._private.events import list_events

        return list_events(limit=limit, source=source)

    @staticmethod
    def _gcs_record_event(source: str, message: str,
                          severity: str = "INFO", metadata=None):
        """Node-forwarded event lands in the head's (observable) buffer."""
        from ray_tpu._private.events import record_event

        record_event(source, message, severity=severity,
                     **(metadata or {}))
        return True

    def _gcs_pg_table(self):
        """Placement-group table as PLAIN data: the in-process table
        holds PlacementGroup handles whose unpickling side-effects a
        full local runtime into an external tool's process."""
        table = self.worker.gcs.placement_group_table()

        def plain(v):
            if isinstance(v, dict):
                return {str(k): plain(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [plain(x) for x in v]
            if isinstance(v, (str, int, float, bool, type(None), bytes)):
                return v
            return str(v)

        return plain(table)

    def _get_nodes(self):
        with self._lock:
            return [
                {"NodeID": n.node_id, "Address": n.address,
                 "Resources": n.resources, "Alive": n.alive,
                 "Available": n.available, "Labels": n.labels,
                 "Stats": n.stats}
                for n in self.nodes.values()
            ]


class ClusterBackendMixin:
    """Installed over the driver's LocalBackend: route specs to nodes."""

    def __init__(self, worker, head: ClusterHead):
        self.worker = worker
        self.head = head
        self.local_backend = worker.backend
        self._rr = 0
        # Lease-based decentralized dispatch (reference:
        # `direct_task_transport.h:75,211` + `lease_policy.h:56`): the
        # head's scheduler is consulted ONCE per task shape to pick a
        # node (locality-aware); subsequent same-shape tasks stream to
        # the leased node over a pipelined channel with no per-task
        # scheduling or round-trip. Leases are returned after
        # `_LEASE_IDLE_S` idle; backlog flows back on resource reports
        # (and, past `sched_spillback_backlog`, spills the lease to a
        # better target). Lease state is LOCK-PARTITIONED by (job,
        # shape) key so concurrent submitters of different shapes never
        # serialize; `_lease_lock` remains the channel/global lock
        # (pipes, batchers, drainer spawn). Ordering rule: shard locks
        # before `_lease_lock`, never the reverse; whole-table
        # operations take every shard lock in index order first.
        self._leases: Dict[tuple, list] = {}
        n_shards = sched_state.round_up_pow2(ray_config.sched_head_shards)
        self._lease_locks = [threading.Lock() for _ in range(n_shards)]
        self._lease_lock = threading.Lock()
        self._pipes: Dict[str, Any] = {}  # node_id -> PipelinedClient
        # node_id -> CoalescingBatcher feeding that node's pipe with
        # submit_batch frames (batched control RPC: many submissions,
        # one framed request + one server dispatch), plus the per-node
        # lock making template-claim + enqueue atomic.
        self._batchers: Dict[str, Any] = {}
        self._submit_locks: Dict[str, Any] = {}
        # (node_id, oid) pairs already pushed (push_manager dedupe).
        self._pushed: set = set()
        # Tenancy: over-CPU-quota specs park in the shared ledger; ONE
        # drainer thread resubmits them as their jobs free capacity
        # (lazily spawned, retires when the park list drains). Actor
        # calls parked for a restart window share the same design: one
        # dispatcher draining the parked list on gate.wait_change —
        # NOT a waiter thread per call.
        self._quota_stop = threading.Event()
        self._quota_drainer: Optional[threading.Thread] = None
        self._parked_calls: list = []
        self._park_lock = threading.Lock()
        self._park_thread: Optional[threading.Thread] = None
        self._fallback_ledger = None

    @property
    def quota_ledger(self):
        # Shared with the local backend (one ledger per head process);
        # harness-built mixins over a stub backend get their own.
        ledger = getattr(self.local_backend, "quota_ledger", None)
        if ledger is None:
            if self._fallback_ledger is None:
                self._fallback_ledger = tenancy.QuotaLedger()
            ledger = self._fallback_ledger
        return ledger

    def submit(self, spec) -> None:
        head = self.head
        if spec.kind == TaskKind.ACTOR_TASK:
            aid = spec.actor_id.binary()
            node_id = head.actor_nodes.get(aid)
            if node_id is not None:
                record = head.nodes.get(node_id)
                if record is None or not record.alive:
                    # The directory still points at a dead node (the
                    # death sweep hasn't run or finished): run it, then
                    # let the gate decide replay-or-reject for THIS
                    # call like any other call caught by the death.
                    # The stale mapping is dropped FIRST — a replay
                    # resubmit must route through the gate, not recurse
                    # back into this branch (mark_node_dead is a no-op
                    # for an already-removed record and would pop
                    # nothing).
                    head.mark_node_dead(node_id,
                                        reason="found dead at dispatch")
                    with head._lock:
                        if head.actor_nodes.get(aid) == node_id:
                            head.actor_nodes.pop(aid, None)
                    head.recover_actor_call(spec)
                    return
                try:
                    self._send(record, spec)
                except (ConnectionError, OSError) as e:
                    # Transport failure: the node itself is
                    # unreachable. mark_node_dead restarts the actor
                    # elsewhere (budget permitting); this call then
                    # replays against the replacement when its own
                    # max_task_retries covers it, else rejects with an
                    # error naming the restart state and budget.
                    head.mark_node_dead(node_id,
                                        reason=f"unreachable: {e}")
                    head.recover_actor_call(spec)
                except Exception as e:
                    # Handler-level error: the node is healthy, this
                    # submission failed — fail the task, keep the node.
                    self._fail_spec(spec, e)
                return
            from ray_tpu._private.actor_gate import ActorRestartState

            state = head.actor_gate.state(aid)
            if state == ActorRestartState.DEAD:
                # Tombstoned (restart budget exhausted): fail FAST with
                # the recorded cause — never fall through to the local
                # backend, which has no such actor and would bury the
                # call behind a generic "unknown actor".
                self._fail_spec(spec, ActorDiedError(
                    spec.actor_id.hex()[:8],
                    head.actor_gate.death_cause(aid)
                    or "restart budget exhausted"))
                return
            if state == ActorRestartState.RESTARTING:
                head.actor_gate.route_call(
                    spec, dispatch=None,
                    park=self._park_actor_call,
                    fail=head._fail_actor_call)
                return
            if state is not None and aid not in head.actor_local:
                # Gate-registered (cluster-dispatched) actor, no
                # location, and not known to live on the head: we
                # raced the death sweep's window between
                # record.alive=False and the gate's RESTARTING flip.
                # Park — falling through to the local backend would
                # fail a retryable call with a generic "unknown
                # actor".
                self._park_actor_call(spec)
                return
            self._submit_local(spec)
            return
        # Tenancy quotas, BEFORE any placement work (reference: lease
        # admission policies): a job at its queued-task ceiling is
        # rejected with a typed error; a job at its CPU quota parks the
        # spec in the ledger — behind its OWN limit, consuming no
        # cluster capacity — until one of its running tasks releases.
        # Both checks are idempotent per spec, so quota-drained
        # resubmits and the local backend's own admission never
        # double-charge.
        if spec.kind in (TaskKind.NORMAL_TASK, TaskKind.ACTOR_CREATION):
            ledger = self.quota_ledger
            reason = ledger.note_queued(spec)
            if reason is not None:
                from ray_tpu.exceptions import JobQuotaExceededError

                self._fail_spec(spec, JobQuotaExceededError(
                    spec.job_id or "", reason))
                return
            if not ledger.try_acquire_cpu(spec):
                if spec.kind == TaskKind.ACTOR_CREATION:
                    # Register the gate BEFORE parking the creation:
                    # method calls submitted meanwhile then park at
                    # the restart gate (ALIVE, no location yet) and
                    # dispatch when the creation finally lands,
                    # instead of failing against an unknown actor.
                    head.record_lineage(spec)
                ledger.park(spec)
                self._ensure_quota_drainer()
                return
        # Strategy-directed routing (reference: the scheduling-policy set
        # of `scheduling/policy/` — PG-affinity, node-affinity, spread).
        routed = self._route_by_strategy(spec)
        if routed is not False:
            return
        # Plain tasks: ONE local-fit check decides — fits → straight to
        # the local backend (the hot path; _choose_node would conclude
        # the same after redundant work); doesn't fit → ride a held
        # lease without per-task head scheduling.
        from ray_tpu._private.task_spec import DefaultSchedulingStrategy

        if spec.kind == TaskKind.NORMAL_TASK and \
                isinstance(spec.scheduling_strategy,
                           (DefaultSchedulingStrategy, type(None))):
            request = _spec_milli_of(spec)
            if self._local_fits_now(request):
                # Locality override: a task whose large args live on a
                # remote node should follow the bytes, not pull them
                # here to follow a small spec.
                if self._locality_prefers_remote(spec) and \
                        self._lease_submit(spec, request):
                    return
                self._submit_local(spec)
                return
            if self._lease_submit(spec, request):
                return
        # Normal tasks / actor creations: try nodes until one accepts.
        attempted: set = set()
        while True:
            target = self._choose_node(spec, exclude=attempted)
            if target is None:
                from ray_tpu._private.resources import to_milli

                request = _spec_milli_of(spec)
                local_total = to_milli(dict(
                    self.local_backend.resources.total))
                if all(local_total.get(k, 0) >= v
                       for k, v in request.items()):
                    if spec.kind != TaskKind.ACTOR_CREATION:
                        # A head-local task may still depend on remote
                        # objects.
                        self._submit_local(spec)
                        return
                    # Lifetime placement: a creation queued on the head
                    # behind lifetime-pinned actor CPUs NEVER constructs
                    # (actors don't release), while a remote node whose
                    # stale report reads full may free on its next
                    # report cycle. Land it locally only when it can
                    # construct NOW; otherwise queue cluster-wide and
                    # let fresh reports (or a local release) decide.
                    if self._submit_local_if_fits(spec, request):
                        return
                # Too big for the head and no remote capacity *right now*:
                # queue cluster-wide (the reference raylet queues leases),
                # failing fast only if no live node could ever fit it.
                if spec.kind == TaskKind.ACTOR_CREATION:
                    # Register the gate BEFORE queueing (mirrors the
                    # quota-park arm): method calls submitted while the
                    # creation waits for capacity park at the gate
                    # (ALIVE, no location yet) and dispatch when it
                    # lands, instead of failing "unknown actor".
                    head.record_lineage(spec)
                self._queue_for_cluster(spec, request)
                return
            if spec.kind == TaskKind.ACTOR_CREATION:
                head.set_actor_node(spec.actor_id.binary(), target.node_id)
                if ray_config.sched_group_actor_creation and \
                        self._send_creation_batched(target, spec):
                    return
            try:
                self._send(target, spec)
                return
            except (ConnectionError, OSError) as e:
                # Not yet in the in-flight table (that happens only after
                # a successful send), so mark_node_dead won't resubmit
                # this spec — the loop retries it on another node.
                attempted.add(target.node_id)
                if spec.kind == TaskKind.ACTOR_CREATION:
                    # Unwind the never-landed placement BEFORE the
                    # death sweep: the sweep must not see this aid in
                    # its dead-actor set — begin_restart would burn
                    # restart budget (tombstoning a max_restarts=0
                    # actor forever) for a creation the loop is about
                    # to retry cleanly elsewhere. The gate's ALIVE flip
                    # rolls back too, so concurrent calls park instead
                    # of dispatching into a backend that has never
                    # heard of the actor.
                    head.actor_nodes.pop(spec.actor_id.binary(), None)
                    head.actor_gate.rollback_ready(
                        spec.actor_id.binary())
                head.mark_node_dead(target.node_id,
                                    reason=f"unreachable: {e}")

    def _fail_spec(self, spec, error: Exception) -> None:
        # Terminal: release any tenancy charges the spec still holds
        # (token-guarded no-ops otherwise).
        ledger = self.quota_ledger
        ledger.note_dequeued(spec)
        ledger.release_cpu(spec)
        store = self.worker.memory_store
        for oid in spec.return_ids:
            store.put(oid, None, error=error)

    def _ensure_quota_drainer(self) -> None:
        with self._lease_lock:
            t = self._quota_drainer
            if t is not None and t.is_alive():
                return
            self._quota_drainer = threading.Thread(
                target=self._quota_drain_loop, daemon=True,
                name="ray_tpu-quota-drain")
            self._quota_drainer.start()

    def _quota_drain_loop(self) -> None:
        """ONE thread drains the quota park list (never a thread per
        parked spec): as a job's running tasks release their CPU
        charges, its parked specs are popped — charged atomically under
        the ledger lock — and re-enter the normal scheduling path."""
        ledger = self.quota_ledger
        while not self._quota_stop.is_set():
            for spec in ledger.take_dispatchable():
                try:
                    self.submit(spec)  # charge held: skips the gate
                except Exception as e:
                    self._fail_spec(spec, e)
            with self._lease_lock:
                if ledger.parked_count() == 0 or \
                        self._quota_stop.is_set():
                    # Retire under the spawn lock: a park landing after
                    # this check sees the dead thread and respawns.
                    self._quota_drainer = None
                    return
            ledger.wait_change(0.5)

    def kill_actor(self, actor_id, no_restart: bool = True) -> None:
        """Deliberate kill in cluster mode: reach the HOSTING node (the
        local backend only knows head-local actors — delegating there
        was a silent no-op for remote ones) and, for no_restart kills,
        tombstone the gate so later calls fail fast with the real
        cause instead of parking or probing a dead mailbox."""
        head = self.head
        aid = actor_id.binary()
        node_id = head.actor_nodes.get(aid)
        if no_restart and head.actor_gate.state(aid) is not None:
            with head._lock:
                head.actor_nodes.pop(aid, None)
            head.actor_gate.mark_dead(
                aid, "killed via ray_tpu.kill(no_restart=True)")
            head.release_actor_quota(aid)
        if node_id is None:
            self.local_backend.kill_actor(actor_id, no_restart)
            return
        record = head.nodes.get(node_id)
        if record is None or not record.alive:
            return  # the death sweep owns cleanup
        try:
            RpcClient.to(record.address).call(
                "kill_actor", actor_id=actor_id, no_restart=no_restart)
        except Exception:
            pass  # node unreachable: the health checker owns it

    def _submit_local(self, spec) -> None:
        """The ONE local-dispatch path in cluster mode: dep fetch +
        local backend, plus the restart gate's ready edge for actor
        creations — a RESTARTED actor that lands on the head (remote
        nodes saturated) has no directory entry (None = head-local),
        but its parked callers must still observe it alive again."""
        self._ensure_local_deps(spec)
        self.local_backend.submit(spec)
        self._local_ready_edge(spec)

    def _local_ready_edge(self, spec) -> None:
        if spec.kind == TaskKind.ACTOR_CREATION:
            aid = spec.actor_id.binary()
            if self.head.actor_gate.state(aid) is not None:
                with self.head._lock:
                    self.head.actor_local.add(aid)
            self.head.actor_gate.ready(aid)

    # Serializes head-local CREATION placement decisions: the fits
    # check and the backend submit (whose pending-demand add IS the
    # claim) must be one atomic step, or concurrent creations all pass
    # the same free CPU and over-pack the head with lifetime-pinned
    # actors that never construct (there is no head-local analogue of
    # _NodeRecord.reserved_milli otherwise).
    _local_place_lock = threading.Lock()

    def _submit_local_if_fits(self, spec, request) -> bool:
        """Atomic check-and-claim for head-local placement of work that
        must be able to START NOW (creations; also safe for tasks).
        Returns False when the head cannot run it immediately."""
        reserve = spec.kind == TaskKind.ACTOR_CREATION
        self._ensure_local_deps(spec)  # may fetch: outside the lock
        with ClusterBackendMixin._local_place_lock:
            if not self._local_fits_now(request,
                                        reserve_dep_parked=reserve):
                return False
            self.local_backend.submit(spec)
        self._local_ready_edge(spec)
        return True

    def _park_actor_call(self, spec) -> None:
        """A call with retry budget submitted during an actor's restart
        window: park in the shared list (the submitter keeps its
        ObjectRef and waits through get()), dispatch when the
        replacement registers, reject when the window expires or the
        actor dies. ONE dispatcher thread drains the whole list on the
        gate's wait_change signal — N parked calls used to cost N
        sleeping waiter threads (the PR 11 accepted trade-off, retired:
        WFQ can park a whole job class's calls at once)."""
        deadline = time.monotonic() + ray_config.actor_restart_timeout_s
        with self._park_lock:
            self._parked_calls.append((spec, deadline))
            t = self._park_thread
            if t is not None and t.is_alive():
                return
            self._park_thread = threading.Thread(
                target=self._park_dispatch_loop, daemon=True,
                name="ray_tpu-actor-park")
            self._park_thread.start()

    def _park_eval(self, spec, deadline: float):
        """Disposition of one parked call: ``None`` = keep parked,
        else a zero-arg effect to run OUTSIDE the park lock."""
        from ray_tpu._private.actor_gate import ActorRestartState

        head = self.head
        aid = spec.actor_id.binary()
        state = head.actor_gate.state(aid)
        if state == ActorRestartState.DEAD:
            cause = head.actor_gate.death_cause(aid) \
                or "actor died during the restart window"
            return lambda: head._fail_actor_call(spec, cause, True)
        # Dispatch only once the actor has a real home again: a node
        # entry, the head itself, or no gate record at all.
        # ALIVE-without-location is the mid-sweep transient —
        # re-submitting there would just re-park.
        if head.actor_nodes.get(aid) is not None or state is None \
                or aid in head.actor_local:
            def dispatch():
                try:
                    self.submit(spec)
                except Exception as e:
                    self._fail_spec(spec, e)
            return dispatch
        if time.monotonic() >= deadline:
            timeout = ray_config.actor_restart_timeout_s
            left = head.actor_gate.restarts_left(aid)
            return lambda: head._fail_actor_call(
                spec,
                f"actor did not become available within "
                f"actor_restart_timeout_s={timeout:g}s (call parked "
                f"with retry budget while the actor was restarting "
                f"or its creation was quota-parked; actor restarts: "
                f"{left} left)",
                False)
        return None

    def _park_dispatch_loop(self) -> None:
        """The one parked-call dispatcher: wakes on every gate
        transition (condition-signalled, no busy polling), sweeps the
        parked list, runs the matured effects outside the lock, and
        retires when the list drains."""
        while not self._quota_stop.is_set():
            effects = []
            with self._park_lock:
                still = []
                for spec, deadline in self._parked_calls:
                    effect = self._park_eval(spec, deadline)
                    if effect is None:
                        still.append((spec, deadline))
                    else:
                        effects.append(effect)
                self._parked_calls = still
            for effect in effects:
                effect()
            with self._park_lock:
                if not self._parked_calls or self._quota_stop.is_set():
                    # Retire under the spawn lock: a park landing after
                    # this check sees the dead thread and respawns.
                    self._park_thread = None
                    return
                nearest = min(d for _s, d in self._parked_calls)
            # Read self.head per iteration: restart_head swaps it.
            self.head.actor_gate.wait_change(
                min(0.5, max(0.01, nearest - time.monotonic())))

    # -- lease-based dispatch (direct_task_transport role) ---------------

    @property
    def _LEASE_IDLE_S(self) -> float:
        return ray_config.sched_lease_idle_s

    # How far a lease may over-subscribe its granted slots before the
    # manager asks the head for another lease on a different node (the
    # reference's backlog-driven extra lease requests).
    _LEASE_BACKLOG_FACTOR = 4

    def _lease_lock_for(self, key: tuple):
        return self._lease_locks[hash(key)
                                 & (len(self._lease_locks) - 1)]

    def _all_lease_locks(self):
        """Acquire every lease shard lock in index order (whole-table
        ops: pipe drops, drains) — deadlock-free against per-key
        holders by the fixed ordering."""
        import contextlib

        stack = contextlib.ExitStack()
        for lock in self._lease_locks:
            stack.enter_context(lock)
        return stack

    def _shape_key(self, spec) -> tuple:
        # Keyed by (job, resource shape): leases are per-TENANT, so
        # the `leases:` quota genuinely bounds a job's pipelined
        # channels — a shape-only key let other jobs ride (and keep
        # alive) a lease charged to whoever asked first, making the
        # cap bound nothing. Untagged traffic shares the "" tenant.
        return (getattr(spec, "job_id", "") or "",) + tuple(
            sorted((k, float(v))
                   for k, v in (spec.resources or {}).items()))

    def _lease_submit(self, spec, request) -> bool:
        """Dispatch through a held (or newly granted) lease; False when
        the task should take the per-task scheduling path instead (no
        node has capacity). Caller has already ruled out local-first."""
        key = self._shape_key(spec)
        now = time.monotonic()
        # A "hit" is a submission with NO head scheduling decision: any
        # _grant_lease attempt (fresh, locality extra, saturated extra,
        # spill) flips it to a miss so hit+miss == submissions and the
        # cache-hit ratio reads true.
        decided = False
        with self._lease_lock_for(key):
            leases = self._leases.get(key)
            if leases:
                # Prune leases on dead nodes and idle-expired ones
                # (lease return: the node's capacity is only "ours"
                # while we keep it busy).
                live, dropped = [], []
                for lease in leases:
                    record = self.head.nodes.get(lease["node_id"])
                    if record is None or not record.alive:
                        dropped.append(lease)
                        continue
                    if lease["pipe"].in_flight == 0 and \
                            now - lease["last_used"] > self._LEASE_IDLE_S:
                        dropped.append(lease)
                        continue
                    live.append(lease)
                if live:
                    self._leases[key] = live
                else:
                    del self._leases[key]
                self._retire_leases(dropped)
                leases = live or None
            if not leases:
                decided = True
                lease = self._grant_lease(key, spec)
                if lease is None:
                    _LEASE_CACHE_MISSES.inc()
                    return False
            else:
                # Leases are keyed by resource SHAPE; a held lease may
                # sit on the wrong node for THIS task's bytes. Prefer a
                # lease already on the locality target, granting one
                # there if none exists yet.
                loc = self._locality_target(spec)
                preferred = [l for l in leases
                             if loc is not None
                             and l["node_id"] == loc.node_id]
                if loc is not None and not preferred:
                    decided = True
                    extra = self._grant_lease(key, spec, target=loc)
                    if extra is not None:
                        preferred = [extra]
                lease = min(preferred or leases,
                            key=lambda l: l["pipe"].in_flight)
                # Saturated: ask for one more lease on another node.
                if lease["pipe"].in_flight >= max(
                        1, lease["slots"]) * self._LEASE_BACKLOG_FACTOR:
                    decided = True
                    extra = self._grant_lease(
                        key, spec,
                        exclude={l["node_id"] for l in leases})
                    if extra is not None:
                        lease = extra
            # Backlog spillback (reference: raylet spillback on deep
            # local queues): the node's own pushed backlog signal says
            # its queue is past the spill threshold — redirect to a
            # lease on a better target (locality-scored grant) instead
            # of piling deeper. The overloaded lease stays held; it
            # re-wins once its backlog drains below the threshold.
            record = self.head.nodes.get(lease["node_id"])
            if record is not None and \
                    record.backlog > ray_config.sched_spillback_backlog:
                spill = None
                if lease.get("spill_denied_at") != record.last_report:
                    decided = True
                    spill = self._grant_lease(
                        key, spec,
                        exclude={l["node_id"]
                                 for l in self._leases.get(key, ())})
                    if spill is None:
                        # Nowhere to GRANT a spill (every candidate
                        # leased or full): stamp the node's report so
                        # saturated submissions stop re-paying the
                        # O(nodes) grant scan until a fresh resource
                        # report changes the picture.
                        lease["spill_denied_at"] = record.last_report
                if spill is None:
                    # Fall back to an already-held lease on a node
                    # whose backlog is below the threshold:
                    # min(in_flight) can keep picking the overloaded
                    # lease (a deep node queue acks frames fast, so
                    # its in_flight stays low), and without this the
                    # flood keeps piling onto it while a healthy
                    # lease idles. O(held leases), so it runs even in
                    # the grant-scan backoff window.
                    thresh = ray_config.sched_spillback_backlog
                    for alt in self._leases.get(key, ()):
                        if alt is lease:
                            continue
                        alt_rec = self.head.nodes.get(alt["node_id"])
                        if alt_rec is None or not alt_rec.alive or \
                                alt_rec.backlog > thresh:
                            continue
                        if spill is None or alt["pipe"].in_flight < \
                                spill["pipe"].in_flight:
                            spill = alt
                if spill is not None:
                    _SPILLBACKS.inc()
                    lease = spill
            lease["last_used"] = now
            (_LEASE_CACHE_MISSES if decided else _LEASE_CACHE_HITS).inc()
        return self._lease_send(lease, spec)

    def _grant_lease(self, key, spec, exclude=(),
                     target=None) -> Optional[dict]:
        """One head scheduling decision for a task SHAPE (not a task):
        locality-aware node choice + slot count from the pushed view.
        Caller holds the key's lease shard lock; a caller that already
        computed the locality target passes it to skip the re-scan."""
        from ray_tpu._private.resources import to_milli

        if target is None:
            target = self._locality_target(spec, exclude)
        if target is None:
            target = self._choose_node(spec, exclude=exclude)
        if target is None:
            return None
        # Concurrent-lease quota: a job at its cap keeps riding the
        # leases it already holds (queueing behind its own limit)
        # instead of opening another pipelined channel.
        job = getattr(spec, "job_id", "") or ""
        if not self.quota_ledger.try_acquire_lease(job):
            return None
        router = getattr(getattr(self, "head", None),
                         "shard_router", None)
        if router is not None:
            # The (job, shape) key's OWNING shard is the registration
            # authority: the grant is recorded there (durably, group-
            # committed) before it exists anywhere else, so one key's
            # grants can never be tracked on two shards and a crashed
            # shard's key range stops granting — callers queue behind
            # their held leases or retry — until the supervisor
            # restarts it, while every other shard keeps granting.
            if not router.lease_register(repr(key).encode(),
                                         target.node_id):
                self.quota_ledger.release_lease(job)
                return None
        request = to_milli(spec.resources)
        slots = 1
        if request:
            slots = max(1, min(
                int(target.available.get(k, 0) * 1000 // v)
                for k, v in request.items() if v > 0))
        pipe = self._node_pipe(target)
        lease = {"node_id": target.node_id, "pipe": pipe,
                 "slots": slots, "last_used": time.monotonic(),
                 "address": target.address, "job": job, "key": key}
        self._leases.setdefault(key, []).append(lease)
        return lease

    def _node_pipe(self, node: "_NodeRecord"):
        """The node's pipelined channel, created on first use. Channel
        registry mutations are under the global channel lock (shard
        lock -> _lease_lock is the one legal nesting order)."""
        with self._lease_lock:
            pipe = self._pipes.get(node.node_id)
            if pipe is None:
                from ray_tpu._private.rpc import PipelinedClient

                pipe = PipelinedClient(node.address,
                                       on_error=self._pipe_error)
                self._pipes[node.node_id] = pipe
            return pipe

    def _retire_leases(self, leases) -> None:
        """Release the lease-quota charge of every retired lease (any
        removal path: idle prune, dead node, broken pipe, drain)."""
        ledger = self.quota_ledger
        # getattr on SELF with a default: `self.head` delegates through
        # __getattr__ to local_backend, which harness-built mixins stub.
        router = getattr(getattr(self, "head", None),
                         "shard_router", None)
        for lease in leases:
            job = lease.get("job")
            if job is not None:
                ledger.release_lease(job)
            if router is not None and lease.get("key") is not None:
                router.lease_retire(repr(lease["key"]).encode(),
                                    lease["node_id"])

    def _arg_bytes_by_addr(self, spec) -> Dict[tuple, int]:
        """Resident argument bytes per owner address, from the head's
        object directory (locations + reported sizes). Cheap when the
        spec has no ObjectRef args — the common fan-out case."""
        from ray_tpu.object_ref import ObjectRef

        head = self.head
        out: Dict[tuple, int] = {}
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if not isinstance(arg, ObjectRef):
                continue
            ob = arg.id.binary()
            loc = head.object_locations.get(ob)
            if loc is None:
                continue
            addr = tuple(loc)
            out[addr] = out.get(addr, 0) + head.object_sizes.get(ob, 0)
        return out

    def _locality_target(self, spec, exclude=()):
        """Lease policy (reference `lease_policy.h:56`): score candidate
        nodes by RESIDENT ARGUMENT BYTES — a task with a 64MB argument
        runs where the bytes already live instead of pulling them to
        follow a 200-byte spec. Ties (equal bytes) fall back to the
        least-loaded ordering the default policy uses; nodes below
        ``locality_min_arg_bytes`` never win on locality alone."""
        if not ray_config.locality_aware_scheduling:
            return None
        bytes_by_addr = self._arg_bytes_by_addr(spec)
        if not bytes_by_addr:
            return None
        from ray_tpu._private.resources import to_milli

        request = to_milli(spec.resources)
        best, best_bytes, best_load = None, 0, -1.0
        for node in self.head.nodes.values():
            if node.node_id in exclude or not node.alive:
                continue
            nbytes = bytes_by_addr.get(tuple(node.address), 0)
            if nbytes < ray_config.locality_min_arg_bytes:
                continue
            if not all(node.available.get(k, 0) * 1000 >= v
                       for k, v in request.items()):
                continue
            load_score = sum(node.available.values()) \
                - 0.1 * node.backlog
            if nbytes > best_bytes or (nbytes == best_bytes
                                       and load_score > best_load):
                best, best_bytes, best_load = node, nbytes, load_score
        return best

    def _locality_prefers_remote(self, spec) -> bool:
        """True when the spec's resident argument bytes make a REMOTE
        node the cheaper home even though the task fits locally (the
        local-first fast path would otherwise pull the bytes here)."""
        if not ray_config.locality_aware_scheduling:
            return False
        bytes_by_addr = self._arg_bytes_by_addr(spec)
        if not bytes_by_addr:
            return False
        local = bytes_by_addr.get(tuple(self.head.server.address), 0)
        remote = max((b for addr, b in bytes_by_addr.items()
                      if addr != tuple(self.head.server.address)),
                     default=0)
        return remote >= ray_config.locality_min_arg_bytes \
            and remote > local

    def _promote_large_args(self, spec):
        """Large plain-value args are published to the object plane and
        replaced by ObjectRefs at the wire boundary, so the TaskCall /
        shipped spec carries a descriptor-resolvable reference instead
        of megabytes of pickle (the reference puts big args in plasma
        at submission). Only obviously-sized values promote (arrays,
        buffers, strings — `nbytes`/`len` is authoritative); containers
        ship as before."""
        plane = getattr(self.worker, "shm_plane", None)
        if plane is None:
            return spec
        from ray_tpu.object_ref import ObjectRef

        threshold = max(int(ray_config.shm_share_threshold_bytes), 1)

        def big(v) -> bool:
            if v is None or isinstance(v, (ObjectRef, bool, int, float)):
                return False
            nbytes = getattr(v, "nbytes", None)
            if isinstance(nbytes, int):
                return nbytes >= threshold
            if isinstance(v, (bytes, bytearray, str)):
                return len(v) >= threshold
            return False

        if not any(big(a) for a in spec.args) and \
                not any(big(v) for v in spec.kwargs.values()):
            return spec
        put = self.worker.put_object
        spec.args = tuple(put(a) if big(a) else a for a in spec.args)
        spec.kwargs = {k: (put(v) if big(v) else v)
                       for k, v in spec.kwargs.items()}
        return spec

    def _lease_send(self, lease, spec) -> bool:
        record = self.head.nodes.get(lease["node_id"])
        if record is None or not record.alive:
            return False
        spec = self._promote_large_args(spec)
        self._publish_local_args(record, spec)
        # Same bookkeeping as _send: lineage + inflight BEFORE the wire.
        self.head.record_lineage(spec)
        self.head.record_inflight(spec, lease["node_id"])
        # Dispatching: the spec leaves the head's queued-ceiling count
        # (its CPU charge stays held until the in-flight entry clears).
        self.quota_ledger.note_dequeued(spec)
        # Coalesced, non-blocking enqueue: the node's batcher drains
        # whatever accumulates while the previous frame is on the wire
        # into ONE submit_batch request. Transport failures surface
        # asynchronously (frame-send fallback / _pipe_error) and
        # re-route through submit() — by then this task is recorded
        # in-flight, so no completion can be lost. The template claim
        # and the enqueue happen under ONE per-node lock: a racing
        # submitter that observes the claim must enqueue BEHIND the
        # claiming item, or its call-only header could reach the node
        # first and hit UnknownTemplate.
        node_id = lease["node_id"]
        for _attempt in range(2):
            with self._submit_lock_for(node_id):
                call, templates = self._wire_item_for(spec, record)
                try:
                    self._batcher_for(node_id, lease["pipe"]).add(
                        (call, templates, spec, lease))
                    return True
                except ConnectionError:
                    # Batcher closed by a concurrent pipe drop: unwind
                    # the claim and retry once with a fresh batcher.
                    for t in templates:
                        record.known_templates.discard(t.template_id)
                    continue
        self.head.clear_inflight(spec)
        return False

    def _send_creation_batched(self, node: "_NodeRecord", spec) -> bool:
        """Group-committed actor creation: the creation rides the
        node's coalescing submit_batch channel — one frame commits a
        GROUP of creations (plus any leased tasks already queued for
        that node, order preserved) instead of one synchronous RPC per
        actor. Bookkeeping is byte-identical to _send — lineage +
        in-flight recorded BEFORE the wire — so a node death re-drives
        the creation through the resubmit loop's inflight_creations
        path (never _restart_actor: no restart budget burned for a
        never-constructed actor) and ActorRestartGate semantics are
        unchanged. Returns False to fall back to the synchronous path
        (channel unavailable/closed)."""
        try:
            pipe = self._node_pipe(node)
        except Exception:
            return False
        spec = self._promote_large_args(spec)
        self._publish_local_args(node, spec)
        self.head.record_lineage(spec)
        self.head.record_inflight(spec, node.node_id)
        self.quota_ledger.note_dequeued(spec)
        # Pseudo-lease tag: the batch error paths only read node_id
        # (and retire via identity against _leases, where this never
        # appears — creations hold no lease-quota charge).
        tag = {"node_id": node.node_id, "pipe": pipe, "job": None}
        with self._submit_lock_for(node.node_id):
            wire_spec = self._strip_exported_func(spec, node)
            try:
                self._batcher_for(node.node_id, pipe).add(
                    (wire_spec, [], spec, tag))
                return True
            except ConnectionError:
                self.head.clear_inflight(spec)
                return False

    def _submit_lock_for(self, node_id: str):
        lock = self._submit_locks.get(node_id)
        if lock is None:
            with self._lease_lock:
                lock = self._submit_locks.setdefault(node_id,
                                                     threading.Lock())
        return lock

    def _batcher_for(self, node_id: str, pipe):
        batcher = self._batchers.get(node_id)
        if batcher is None:
            with self._lease_lock:
                batcher = self._batchers.get(node_id)
                if batcher is None:
                    from ray_tpu._private.rpc import CoalescingBatcher

                    batcher = CoalescingBatcher(
                        lambda batch, nid=node_id, p=pipe:
                        self._send_submit_frame(nid, p, batch),
                        name=f"submit-{node_id}")
                    self._batchers[node_id] = batcher
        return batcher

    def _wire_item_for(self, spec, record: "_NodeRecord"):
        """The wire form of one submission: a TaskCall header against an
        interned template (plus the template itself on its first trip to
        this node), or the full spec for shapes that can't intern
        (actor tasks, unexportable functions)."""
        from ray_tpu._private import wire
        from ray_tpu._private.task_spec import get_template

        if spec.kind == TaskKind.NORMAL_TASK and spec.template_id \
                and spec.func_id:
            # A compact header carries its template strongly — immune
            # to intern-cache eviction; full specs re-resolve by id.
            tpl = getattr(spec, "tpl", None) or \
                get_template(spec.template_id)
            if tpl is not None:
                templates = []
                if spec.template_id not in record.known_templates:
                    # Claimed optimistically; racing submitters may ship
                    # the template twice, which registers idempotently.
                    record.known_templates.add(spec.template_id)
                    templates.append(wire.TaskTemplate(
                        template_id=spec.template_id,
                        payload=wire.Opaque(tpl)))
                call = wire.TaskCall(
                    template_id=spec.template_id,
                    task_id=spec.task_id.binary(),
                    args=wire.Opaque(spec.args) if spec.args else None,
                    kwargs=wire.Opaque(spec.kwargs) if spec.kwargs else None,
                    num_returns=spec.num_returns,
                    depth=spec.depth,
                    trace_parent=spec.trace_parent,
                    max_retries=spec.max_retries,
                    job_id=spec.job_id or "",
                    attempt=getattr(spec, "attempt", 0))
                return call, templates
        return self._strip_exported_func(spec, record), []

    def _send_submit_frame(self, node_id: str, pipe, batch) -> None:
        """Flush one coalesced batch as a single submit_batch request.
        Encode failures retry items individually (so one unpicklable
        payload fails alone); transport failures re-route every item
        through submit()."""
        templates, calls, tags = [], [], []
        for call, tpls, spec, lease in batch:
            templates.extend(tpls)
            calls.append(call)
            tags.append((spec, lease))
        kwargs = {"templates": templates, "calls": calls}
        try:
            pipe.send("submit_batch", tag=("__batch__", tags, kwargs),
                      **kwargs)
            return
        except (ConnectionError, OSError):
            # The claiming frame never arrived: un-claim its templates
            # or every later TaskCall of these shapes to this (still
            # alive) node would hit UnknownTemplate forever.
            record = self.head.nodes.get(node_id)
            if record is not None:
                for t in templates:
                    record.known_templates.discard(t.template_id)
            self._drop_lease_pipe(node_id, None)
            for spec, lease in tags:
                self.head.clear_inflight(spec)
                try:
                    self.submit(spec)
                except Exception as e:
                    self._fail_spec(spec, e)
            return
        except BaseException as e:  # encode failure (unpicklable payload)
            if len(batch) == 1:
                # The frame (and any template it carried) never reached
                # the node: un-claim, or later call-only headers of this
                # shape would hit UnknownTemplate forever.
                record = self.head.nodes.get(node_id)
                if record is not None:
                    for t in templates:
                        record.known_templates.discard(t.template_id)
                spec = batch[0][2]
                self.head.clear_inflight(spec)
                self._fail_spec(spec, e)
                return
            for item in batch:
                self._send_submit_frame(node_id, pipe, [item])

    def drain_channels(self, timeout: float = 2.0) -> None:
        """Shutdown-boundary drain: flush-and-close every submit
        batcher and pipelined channel so accepted submissions reach the
        wire (and are acked) before the cluster tears down. Also stops
        the tenancy drainer + parked-call dispatcher threads (their
        parked work is abandoned with the cluster)."""
        self._quota_stop.set()
        # Bounded joins: both loops wake within their 0.5s wait slice,
        # observe the stop flag, and retire.
        for t in (self._quota_drainer, self._park_thread):
            if t is not None and t.is_alive():
                t.join(timeout=1.0)
        with self._all_lease_locks():
            self._retire_leases(
                [l for ls in self._leases.values() for l in ls])
            self._leases.clear()
        with self._lease_lock:
            batchers = list(self._batchers.values())
            pipes = list(self._pipes.values())
            self._batchers.clear()
            self._pipes.clear()
        for batcher in batchers:
            batcher.close(drain_timeout=timeout)
        for pipe in pipes:
            pipe.close(flush_timeout=timeout)

    def _drop_lease_pipe(self, node_id: str, lease) -> None:
        # Pop the channel FIRST: a concurrent _grant_lease racing this
        # drop then mints a fresh pipe (and batcher) via _node_pipe
        # instead of binding a new lease to the broken one about to be
        # closed — those sends would fail and burn the spec's bounded
        # lease reroutes on a node that may be healthy. A lease granted
        # in the window is swept by the retirement pass below and
        # simply re-grants on its next use.
        with self._lease_lock:
            pipe = self._pipes.pop(node_id, None)
            batcher = self._batchers.pop(node_id, None)
        with self._all_lease_locks():
            retired = []
            for ls in self._leases.values():
                if lease is None:
                    retired += [l for l in ls
                                if l["node_id"] == node_id]
                    ls[:] = [l for l in ls if l["node_id"] != node_id]
                elif lease in ls:
                    retired.append(lease)
                    ls[:] = [l for l in ls if l is not lease]
            self._retire_leases(retired)
        if batcher is not None:
            batcher.close()  # flusher drains then retires (no thread leak)
        if pipe is not None:
            pipe.close()  # immediate: the channel is already broken

    def _pipe_error(self, tag, message: str, rid: str, lost: bool):
        """Async failure from a pipelined channel (reader thread)."""
        if isinstance(tag, tuple) and len(tag) == 3 and \
                tag[0] == "__batch__":
            return self._batch_pipe_error(tag, message, rid, lost)
        spec, lease = tag
        if not lost:
            # The node processed the request but its HANDLER failed —
            # a control-plane problem (function-resolution hiccup,
            # queue rejection), not a user-code error (those land in
            # the result object). Re-route through the per-task
            # scheduling path like the non-leased loop would, bounded
            # so a deterministic failure still surfaces.
            self.head.clear_inflight(spec)
            retries = getattr(spec, "_lease_reroutes", 0)
            if retries < 3:
                spec._lease_reroutes = retries + 1
                with self._all_lease_locks():
                    retired = []
                    for ls in self._leases.values():
                        if lease in ls:
                            retired.append(lease)
                            ls[:] = [l for l in ls if l is not lease]
                    self._retire_leases(retired)
                try:
                    self.submit(spec)
                    return
                except Exception:
                    pass
            self._fail_spec(spec, RuntimeError(
                f"leased submit failed on {lease['node_id']} after "
                f"{retries} reroutes: {message}"))
            return
        # Connection lost with the request un-acked: resubmit under the
        # SAME request id — the node's dedupe cache makes this exactly-
        # once whether or not the original arrived. If the node is
        # truly dead, the inflight table resubmits via mark_node_dead.
        record = self.head.nodes.get(lease["node_id"])
        # Pop the broken pipe BEFORE retiring the lease (same order as
        # _drop_lease_pipe): a _grant_lease racing this handler must
        # mint a fresh pipe, not bind a new lease to the dead one and
        # burn the spec's bounded reroutes on a healthy node.
        with self._lease_lock:
            self._pipes.pop(lease["node_id"], None)
        with self._all_lease_locks():
            retired = []
            for ls in self._leases.values():
                if lease in ls:
                    retired.append(lease)
                    ls[:] = [l for l in ls if l is not lease]
            self._retire_leases(retired)
        if record is None or not record.alive:
            return  # node-death sweep owns recovery
        try:
            wire_spec = self._strip_exported_func(spec, record)
            RpcClient.to(record.address).call_with_rid(
                rid, "submit_task", spec=wire_spec)
        except Exception as e:
            self.head.clear_inflight(spec)
            self.head.mark_node_dead(lease["node_id"],
                                     reason=f"unreachable: {e}")

    def _batch_pipe_error(self, tag, message: str, rid: str, lost: bool):
        """Failure of one coalesced submit_batch frame. Non-lost means
        the node received and dispatched the frame but the HANDLER
        failed wholesale (per-call failures never reach here — the node
        stores those into the calls' return objects): re-route every
        item. Lost means the connection died un-acked: resubmit the
        whole frame under the SAME request id — the node's dedupe cache
        makes that exactly-once."""
        _, tags, kwargs = tag
        node_id = tags[0][1]["node_id"] if tags else None
        record = self.head.nodes.get(node_id) if node_id else None
        if not lost:
            # The node rejected the frame WHOLESALE (decode/handler
            # failure before dispatch): its templates never registered,
            # so un-claim them or every later call-only header of these
            # shapes fails with UnknownTemplate forever.
            if record is not None:
                for t in kwargs.get("templates") or []:
                    record.known_templates.discard(t.template_id)
            for spec, lease in tags:
                self.head.clear_inflight(spec)
            if node_id is not None:
                self._drop_lease_pipe(node_id, None)
            for spec, _lease in tags:
                retries = getattr(spec, "_lease_reroutes", 0)
                if retries < 3:
                    spec._lease_reroutes = retries + 1
                    try:
                        self.submit(spec)
                        continue
                    except Exception:
                        pass
                self._fail_spec(spec, RuntimeError(
                    f"batched submit failed on {node_id}: {message}"))
            return
        if node_id is not None:
            self._drop_lease_pipe(node_id, None)
        if record is None or not record.alive:
            return  # node-death sweep owns recovery
        try:
            RpcClient.to(record.address).call_with_rid(
                rid, "submit_batch", **kwargs)
        except Exception as e:
            for spec, _lease in tags:
                self.head.clear_inflight(spec)
            self.head.mark_node_dead(node_id,
                                     reason=f"unreachable: {e}")

    def _route_by_strategy(self, spec):
        """Route a spec per its scheduling strategy. Returns False when
        the default (hybrid local-first) policy should decide instead."""
        from ray_tpu._private.task_spec import (
            NodeAffinitySchedulingStrategy,
            PlacementGroupSchedulingStrategy,
            SpreadSchedulingStrategy,
        )
        from ray_tpu import exceptions as exc

        strat = spec.scheduling_strategy
        head = self.head

        if isinstance(strat, PlacementGroupSchedulingStrategy) and \
                strat.placement_group is not None:
            pg = strat.placement_group
            # Resolve the canonical handle (serialized handles may be
            # detached reconstructions with a stale ready bit).
            canonical = self.worker.gcs.placement_group_table().get(pg.id)
            if canonical is not None:
                pg = canonical
            pgid = pg.id.binary()
            idx = strat.placement_group_bundle_index
            if not pg._ready.is_set():
                # Reservation still in flight: queue until it commits
                # (the reference queues PG-targeted leases likewise).
                def wait_then_submit(spec=spec, pg=pg):
                    pg._ready.wait(timeout=300)
                    self.submit(spec)

                threading.Thread(target=wait_then_submit, daemon=True,
                                 name="ray_tpu-pg-wait").start()
                return True
            if pg._failed:
                self._fail_spec(spec, exc.PlacementGroupSchedulingError(
                    f"placement group reservation failed: {pg._failed}"))
                return True
            entries = {k: v for k, v in head.pg_bundle_nodes.items()
                       if k[0] == pgid}
            if not entries:
                return False  # single-node PG (head-local pools)
            if idx >= 0:
                node_id = entries.get((pgid, idx), "__missing__")
                if node_id == "__missing__":
                    self._fail_spec(spec, exc.PlacementGroupSchedulingError(
                        f"bundle {idx} of placement group is not reserved"))
                    return True
            else:
                # Any bundle: prefer one on this (head) node, else first.
                node_id = None if None in entries.values() else \
                    next(iter(entries.values()))
            if node_id is None:
                self._submit_local(spec)
                return True
            record = head.nodes.get(node_id)
            if record is None or not record.alive:
                self._fail_spec(spec, exc.PlacementGroupSchedulingError(
                    f"placement group bundle's node {node_id} is dead"))
                return True
            if spec.kind == TaskKind.ACTOR_CREATION:
                head.set_actor_node(spec.actor_id.binary(), record.node_id)
            try:
                self._send(record, spec)
            except (ConnectionError, OSError) as e:
                if spec.kind == TaskKind.ACTOR_CREATION:
                    # Unwind the never-landed placement BEFORE the
                    # sweep (see submit's creation handler).
                    head.actor_nodes.pop(spec.actor_id.binary(), None)
                    head.actor_gate.rollback_ready(
                        spec.actor_id.binary())
                head.mark_node_dead(record.node_id,
                                    reason=f"unreachable: {e}")
                self._fail_spec(spec, exc.PlacementGroupSchedulingError(
                    f"placement group bundle's node {node_id} became "
                    f"unreachable: {e}"))
            return True

        if isinstance(strat, NodeAffinitySchedulingStrategy) and \
                strat.node_id is not None:
            wanted = strat.node_id
            if isinstance(wanted, bytes):
                wanted = wanted.decode()
            record = head.nodes.get(str(wanted))
            if record is None or not record.alive:
                if strat.soft:
                    return False
                self._fail_spec(spec, RuntimeError(
                    f"node affinity target {wanted!r} is not available"))
                return True
            if spec.kind == TaskKind.ACTOR_CREATION:
                head.set_actor_node(spec.actor_id.binary(), record.node_id)
            try:
                self._send(record, spec)
            except (ConnectionError, OSError) as e:
                if spec.kind == TaskKind.ACTOR_CREATION:
                    head.actor_nodes.pop(spec.actor_id.binary(), None)
                    head.actor_gate.rollback_ready(
                        spec.actor_id.binary())
                head.mark_node_dead(record.node_id,
                                    reason=f"unreachable: {e}")
                if strat.soft:
                    return False
                self._fail_spec(spec, RuntimeError(
                    f"node affinity target {wanted!r} became unreachable"))
            return True

        if isinstance(strat, SpreadSchedulingStrategy):
            # Round-robin over head + alive nodes with capacity
            # (reference: spread_scheduling_policy.h:27).
            from ray_tpu._private.resources import to_milli

            request = to_milli(spec.resources)
            slots: List[Optional[_NodeRecord]] = [None]
            slots += [n for n in head.nodes.values() if n.alive]
            for attempt in range(len(slots)):
                target = slots[(self._rr + attempt) % len(slots)]
                if target is None:
                    local = self.local_backend.resources
                    with local._cond:
                        fits = all(local._available.get(k, 0) >= v
                                   for k, v in request.items())
                    if not fits:
                        continue
                    self._rr += attempt + 1
                    self._submit_local(spec)
                    return True
                if all(target.available.get(k, 0) * 1000 >= v
                       for k, v in request.items()):
                    self._rr += attempt + 1
                    if spec.kind == TaskKind.ACTOR_CREATION:
                        head.set_actor_node(spec.actor_id.binary(),
                                            target.node_id)
                    try:
                        self._send(target, spec)
                        return True
                    except (ConnectionError, OSError) as e:
                        if spec.kind == TaskKind.ACTOR_CREATION:
                            head.actor_nodes.pop(
                                spec.actor_id.binary(), None)
                            head.actor_gate.rollback_ready(
                                spec.actor_id.binary())
                        head.mark_node_dead(target.node_id,
                                            reason=f"unreachable: {e}")
                        continue
            return False  # nothing fits now: fall back to default queueing

        return False

    def _ensure_local_deps(self, spec):
        from ray_tpu.object_ref import ObjectRef

        store = self.worker.memory_store
        head = self.head
        missing = [a.id for a in
                   list(spec.args) + list(spec.kwargs.values())
                   if isinstance(a, ObjectRef) and not store.contains(a.id)]
        for oid in missing:
            def fetch(oid=oid):
                if try_shm_fetch(self.worker, oid):
                    return
                # Transport failures are retried until the deadline (a
                # brief owner stall must not poison the object); if the
                # owner stayed unreachable the whole window, `get` raises
                # OwnerDiedError instead of hanging. A never-located
                # object is left pending — its producer may just be slow.
                from ray_tpu._private.config import ray_config

                deadline = time.monotonic() + ray_config.fetch_deadline_s
                transport_err = None
                attempt = 0
                while time.monotonic() < deadline:
                    if store.contains(oid):
                        return
                    info = head._locate2(oid.binary())
                    if info is not None and \
                            tuple(info["address"]) != head.server.address:
                        if try_transfer_fetch(self.worker, oid, info):
                            return
                        try:
                            ok, value, err = RpcClient.to(
                                tuple(info["address"])).call(
                                "get_object", oid=oid.binary())
                        except Exception as e:
                            transport_err = e
                            time.sleep(0.2)
                            continue
                        if ok:
                            store.put(oid, value, error=err)
                            return
                    fetch_backoff(attempt)
                    attempt += 1
                if transport_err is not None and not store.contains(oid):
                    store.put(oid, None, error=OwnerDiedError(
                        oid.hex()[:12],
                        f"owner of {oid.hex()[:12]} unreachable past the fetch deadline: "
                        f"{transport_err}"))

            threading.Thread(target=fetch, daemon=True).start()

    def _queue_for_cluster(self, spec, request) -> None:
        """Background retry until some node frees capacity (or none could
        ever fit). Keeps the head's LocalBackend out of it: its hard
        infeasibility check is per-node, not cluster-wide."""
        from ray_tpu._private.resources import to_milli
        from ray_tpu import exceptions as exc

        tid = spec.task_id.binary()
        self.head.pending_demands[tid] = dict(spec.resources) \
            or {"CPU": 1.0}

        def loop():
            try:
                local_total = to_milli(dict(
                    self.local_backend.resources.total))
                local_possible = all(local_total.get(k, 0) >= v
                                     for k, v in request.items())
                while True:
                    feasible = local_possible
                    for record in self.head.nodes.values():
                        if feasible:
                            break
                        if not record.alive:
                            continue
                        total = to_milli(dict(record.resources))
                        if all(total.get(k, 0) >= v
                               for k, v in request.items()):
                            feasible = True
                            break
                    if not feasible and \
                            not self.head.autoscaling_enabled:
                        # No autoscaler: nothing will ever fit — fail
                        # fast. With one, stay pending: the demand is
                        # what makes the autoscaler launch capacity.
                        self._fail_spec(spec, exc.RayTpuError(
                            f"task {spec.describe()} requests "
                            f"{spec.resources} which no live cluster "
                            "node can satisfy"))
                        return
                    target = (self._choose_node(spec, exclude=())
                              if feasible else None)
                    if target is not None:
                        if spec.kind == TaskKind.ACTOR_CREATION:
                            self.head.set_actor_node(
                                spec.actor_id.binary(), target.node_id)
                        try:
                            self._send(target, spec)
                            return
                        except (ConnectionError, OSError) as e:
                            if spec.kind == TaskKind.ACTOR_CREATION:
                                # Unwind BEFORE the sweep (see submit).
                                self.head.actor_nodes.pop(
                                    spec.actor_id.binary(), None)
                                self.head.actor_gate.rollback_ready(
                                    spec.actor_id.binary())
                            self.head.mark_node_dead(
                                target.node_id,
                                reason=f"unreachable: {e}")
                    elif local_possible and \
                            self._submit_local_if_fits(spec, request):
                        # _choose_node returns None both for "the head
                        # fits it now" and "nothing remote fits" —
                        # dispatch locally only in the first case (a
                        # queued CREATION must construct immediately,
                        # never park behind lifetime-pinned CPUs; the
                        # atomic check-and-claim stops concurrent queue
                        # threads from over-packing one freed CPU).
                        return
                    time.sleep(0.1)
            finally:
                self.head.pending_demands.pop(tid, None)

        threading.Thread(target=loop, daemon=True,
                         name="ray_tpu-cluster-queue").start()

    def _local_fits_now(self, request,
                        reserve_dep_parked: bool = False) -> bool:
        """Run/construct-NOW feasibility on the head's local backend:
        available minus already-queued demand covers the milli request.
        ``reserve_dep_parked`` additionally reserves for dep-parked
        work — lifetime-pinned CREATIONS must see it (a dep-blocked
        burst's demand is invisible to the backlog counter until the
        deps resolve, by which time over-landed creations park behind
        pinned CPUs forever); plain tasks queue and release, so they
        keep the cheaper check."""
        local = self.local_backend.resources
        pending = self.local_backend.pending_demand_milli()
        dep_parked = (self.local_backend.dep_parked_demand_milli()
                      if reserve_dep_parked else {})
        with local._cond:
            return all(
                local._available.get(k, 0) - pending.get(k, 0)
                - dep_parked.get(k, 0) >= v
                for k, v in request.items())

    def _choose_node(self, spec, exclude=()) -> Optional[_NodeRecord]:
        """Local-first pack; spill to remote capacity when local can't run
        it now (reference hybrid policy shape)."""
        request = _spec_milli_of(spec)
        if self._local_fits_now(
                request,
                reserve_dep_parked=spec.kind == TaskKind.ACTOR_CREATION):
            return None
        # Pushed resource view (ray_syncer role): no per-submit pings.
        # Staleness is fine — the receiving node queues anything that no
        # longer fits, and the next report corrects the view.
        candidates = [n for n in self.head.nodes.values()
                      if n.alive and n.node_id not in exclude]
        best, best_avail = None, -1.0
        for node in candidates:
            avail = node.available
            reserved = node.reserved_milli
            if all(avail.get(k, 0) * 1000 - reserved.get(k, 0) >= v
                   for k, v in request.items()):
                # Reported backlog discounts a node that looks free but
                # has a deep queue (lease pipelining fills queues ahead
                # of the availability view).
                score = sum(avail.values()) - 0.1 * node.backlog
                if score > best_avail:
                    best, best_avail = node, score
        return best

    # Args at or above this size are PUSHED to the target node ahead of
    # the task (reference push_manager.h: proactive transfers beat the
    # node's on-demand dep pull by one full round trip + queue wait).
    _PUSH_ARG_BYTES = 4 << 20

    def _publish_local_args(self, node: _NodeRecord, spec) -> None:
        """The ONE publish path both dispatch flavors share: report
        driver-local arg locations to the head, then proactively push
        big ones to the target node (off-thread, deduped — the node's
        on-demand dep fetch remains the fallback for every miss)."""
        from ray_tpu.object_ref import ObjectRef

        store = self.worker.memory_store
        local_refs = [arg for arg in list(spec.args)
                      + list(spec.kwargs.values())
                      if isinstance(arg, ObjectRef)
                      and store.contains(arg.id)]
        if not local_refs:
            return
        local_oids = [arg.id.binary() for arg in local_refs]
        self.head._report_objects(
            local_oids, self.head.server.address,
            sizes=[store.entry_size(arg.id) for arg in local_refs])
        self._maybe_push_args(node, local_oids)

    def _maybe_push_args(self, node: _NodeRecord, local_oids) -> None:
        plane = getattr(self.worker, "shm_plane", None)
        if plane is None or node.transfer is None or \
                node.shm_name == plane.name:
            return  # shared segment: dep is already zero-copy visible
        to_push = []
        for ob in local_oids:
            key = (node.node_id, ob)
            if key in self._pushed:
                continue
            try:
                size = plane.store.object_size(ob)
            except Exception:
                size = None
            if size is None or size < self._PUSH_ARG_BYTES:
                continue
            self._pushed.add(key)  # claim before the async push races
            to_push.append(ob)
        if not to_push:
            return

        def run(addr=node.transfer, oids=to_push, nid=node.node_id):
            for ob in oids:
                try:
                    rc = plane.store.push_to(ob, addr[0], addr[1])
                    if rc not in (0, -5):
                        self._pushed.discard((nid, ob))
                except Exception:
                    self._pushed.discard((nid, ob))

        # Off the dispatch path: a GB-scale push must never stall
        # submission; the dep fetch covers the in-flight window.
        threading.Thread(target=run, daemon=True,
                         name="arg-push").start()

    def _send(self, node: _NodeRecord, spec):
        spec = self._promote_large_args(spec)
        # Ordering fence: this synchronous submission must not overtake
        # coalesced frames already enqueued for the same node on the
        # pipelined channel (e.g. tasks submitted just before an actor
        # creation that will pin the node's resources). Flush the
        # batcher (frames handed to the socket) and the pipe (frames
        # ACKED, i.e. dispatched node-side) first; both are no-ops on
        # idle channels and best-effort on sick ones — the node-death
        # paths own real failures.
        batcher = self._batchers.get(node.node_id)
        if batcher is not None:
            batcher.flush(timeout=30.0)
            pipe = self._pipes.get(node.node_id)
            if pipe is not None:
                pipe.flush(timeout=30.0)
        self._publish_local_args(node, spec)
        # Lineage + in-flight BEFORE the wire: a fast task can execute
        # and report its outputs before this function returns, and that
        # report must find (and clear) the in-flight entry — recording
        # after the ack leaves a stale entry that a later node-death
        # sweep would re-drive as a duplicate. On send failure the entry
        # is cleared before the caller's mark_node_dead sweep runs, so
        # only the caller retries.
        self.head.record_lineage(spec)
        self.head.record_inflight(spec, node.node_id)
        self.quota_ledger.note_dequeued(spec)
        wire_spec = self._strip_exported_func(spec, node)
        try:
            RpcClient.to(node.address).call("submit_task",
                                            spec=wire_spec)
        except BaseException:
            self.head.clear_inflight(spec)
            raise

    def _strip_exported_func(self, spec, node: "_NodeRecord"):
        """Function-distribution cache (reference: function_manager
        export via GCS KV + worker import thread). The first shipment of
        a function to the cluster exports its cloudpickle to the head KV
        under its content hash; once a node has seen the id, later task
        specs travel WITHOUT the function body (often the bulk of a
        small task's wire bytes) and the node re-resolves from its local
        cache, falling back to the head KV."""
        from ray_tpu._private.task_spec import QueuedTaskHeader

        if type(spec) is QueuedTaskHeader:
            # Full-spec shipping boundary: materialize the header for
            # the wire WITHOUT moving its quota tokens — the head keeps
            # the header in its lineage/in-flight tables, and releases
            # must find the charge there, not on the wire copy.
            spec = spec.materialize(transfer_tokens=False)
        fid = getattr(spec, "func_id", None)
        if fid is None or spec.kind == TaskKind.ACTOR_TASK:
            return spec
        head = self.head
        if fid not in head.exported_fns:
            from ray_tpu.remote_function import get_export_blob

            blob = get_export_blob(fid)
            if blob is None:
                # No registry entry in THIS process (e.g. spec arrived
                # through the ray-client server): re-pickle, and key the
                # export by the hash of what we actually store — the
                # KV blob and its id must never diverge.
                import hashlib

                import cloudpickle

                try:
                    blob = cloudpickle.dumps(spec.func)
                except Exception:
                    return spec  # unexportable: ship inline as before
                actual = hashlib.sha1(blob).digest()
                if actual != fid:
                    fid = actual
                    import copy

                    spec = copy.copy(spec)
                    spec.func_id = fid
            if fid not in head.exported_fns:
                try:
                    head.worker.gcs.kv_put(fid, blob,
                                           namespace=b"__fn__")
                except Exception:
                    return spec
                head.exported_fns.add(fid)
        if fid in node.known_fns:
            import copy

            wire_spec = copy.copy(spec)
            wire_spec.func = None
            return wire_spec
        node.known_fns.add(fid)  # first shipment carries the body
        return spec

    def shutdown(self):
        """Stop the mixin's own threads (quota drainer, parked-call
        dispatcher), then the local backend's engine."""
        self._quota_stop.set()
        for t in (self._quota_drainer, self._park_thread):
            if t is not None and t.is_alive():
                t.join(timeout=1.0)
        self.local_backend.shutdown()

    # Delegate everything else to the local backend.

    def __getattr__(self, name):
        if name == "local_backend":
            # A half-constructed mixin (harness __new__) must raise,
            # not recurse through this delegation forever.
            raise AttributeError(name)
        return getattr(self.local_backend, name)


class ClusterDriverMixin:
    """get()/wait() that pull remote objects on demand."""

    @staticmethod
    def install(worker, head: ClusterHead):
        worker.cluster_head = head
        original_get = worker.get_objects
        original_wait = worker.wait
        # Both driver-plumbing threads (fetch dispatcher + release
        # batcher) stop through this event at worker shutdown: daemon
        # threads die with the PROCESS, but a long-lived process
        # (test suite, multi-job driver) reconnects and must get its
        # threads back — the leak sanitizer enforces it.
        plumbing_stop = threading.Event()

        # ONE event-driven fetch dispatcher instead of a polling thread
        # per awaited ref (reference: pull_manager.h:52 — a single pull
        # manager with location-notification wakeups). A thread per ref
        # melts down at fan-out scale: 2k awaited refs = 2k threads
        # spinning locate2 polls, starving the executors they wait on.
        # The head's report_objects handler NOTIFIES the dispatcher, so
        # the common case is exactly one fetch attempt per object, right
        # when it becomes available; a slow sweep covers stragglers.
        pending: Dict[bytes, dict] = {}
        cond = threading.Condition()
        hot: set = set()

        def _resolved_locally(object_id):
            # The object landed in the local store (local execution, or
            # a completed fetch): retire its pending entry so the sweep
            # never has to scan resolved refs.
            with cond:
                pending.pop(object_id.binary(), None)

        def ensure_fetch(ref):
            if worker.memory_store.contains(ref.id):
                return
            from ray_tpu._private.config import ray_config

            key = ref.id.binary()
            # First attempt only when the object is ALREADY located
            # somewhere (get-after-completion); otherwise stay purely
            # event-driven — probing shm/directory per awaited ref costs
            # more than the fan-out being awaited.
            with cond:
                if key in pending:
                    return
                pending[key] = {
                    "ref": ref,
                    "deadline": time.monotonic()
                    + ray_config.fetch_deadline_s,
                    "err": None,
                }
            # Location check AFTER the pending insert: a report landing
            # between a pre-insert check and the insert would notify
            # nobody and strand the ref until the slow sweep.
            if key in worker.cluster_head.object_locations:
                with cond:
                    hot.add(key)
                    cond.notify()
            worker.memory_store.on_ready(ref.id, _resolved_locally)

        def on_objects_reported(oids):
            with cond:
                wanted = [o for o in oids if o in pending]
                if wanted:
                    hot.update(wanted)
                    cond.notify()

        worker._fetch_notify = on_objects_reported

        def try_fetch_batch(items) -> set:
            """Batched fetch round over the shared pull core (a
            completed fan-out used to drain with one synchronous round
            trip per object). Returns resolved keys; failures leave
            their error on the entry for deadline handling."""
            # Read through worker.cluster_head (not the install-time
            # capture): restart_head swaps it.
            live_head = worker.cluster_head

            def locate(need):
                return [live_head._locate2(o.binary()) for o in need]

            resolved, failed, _unresolved = batch_fetch_objects(
                worker, [entry["ref"].id for _key, entry in items],
                locate, live_head.server.address)
            done: set = set()
            for key, entry in items:
                oid = entry["ref"].id
                if oid in resolved:
                    done.add(key)
                elif oid in failed:
                    entry["err"] = failed[oid]
            return done

        def dispatcher():
            # Notifications (head reports + local-store callbacks) carry
            # the fast path; the periodic full sweep is only the safety
            # net for missed reports, so it can be SLOW — sweeping every
            # pending ref at high frequency burns the very core the
            # executors need.
            sweep_at = 0.0
            while not plumbing_stop.is_set():
                with cond:
                    cond.wait(timeout=0.05)
                    batch = list(hot)
                    hot.clear()
                    # The sweep runs ON SCHEDULE, not only on idle
                    # cycles — steady hot traffic must never starve the
                    # stragglers the sweep exists to rescue.
                    if pending and time.monotonic() >= sweep_at:
                        batch = list(pending)
                        sweep_at = time.monotonic() + 1.0
                now = time.monotonic()
                items = []
                with cond:
                    for key in batch:
                        entry = pending.get(key)
                        if entry is not None:
                            items.append((key, entry))
                try:
                    done_keys = try_fetch_batch(items)
                except Exception as e:
                    done_keys = set()
                    for _key, entry in items:
                        entry["err"] = e
                for key, entry in items:
                    done = key in done_keys
                    if not done and now > entry["deadline"]:
                        done = True
                        if entry["err"] is not None and \
                                not worker.memory_store.contains(
                                    entry["ref"].id):
                            worker.memory_store.put(
                                entry["ref"].id, None,
                                error=OwnerDiedError(
                                    entry["ref"].id.hex()[:12],
                                    "owner unreachable past the fetch "
                                    f"deadline: {entry['err']}"))
                    if done:
                        with cond:
                            pending.pop(key, None)
                # Drop loop locals: a lingering `entry` binding would
                # pin its ObjectRef (blocking the driver's zero-ref
                # release) across the next wait.
                entry = batch = items = done_keys = None

        dispatcher_thread = threading.Thread(
            target=dispatcher, daemon=True,
            name="cluster-fetch-dispatcher")
        dispatcher_thread.start()

        def get_objects(refs, timeout=None):
            for ref in refs:
                ensure_fetch(ref)
            return original_get(refs, timeout)

        def wait(refs, num_returns, timeout, fetch_local=True):
            for ref in refs:
                ensure_fetch(ref)
            return original_wait(refs, num_returns, timeout, fetch_local)

        worker.get_objects = get_objects
        worker.wait = wait

        # -- distributed release: when the driver's refcount for an
        # object hits zero, batch-release it cluster-wide (owner node
        # drops its copy; lineage unpins). Reference: ReferenceCounter
        # release → FreeObjects fan-out.
        import queue as _queue

        release_q: _queue.Queue = _queue.Queue()
        original_unregister = worker.unregister_object_ref
        original_register = worker.register_object_ref

        def register(ref):
            count = original_register(ref)
            if count == 1:
                # Re-acquiring a handle the driver had fully dropped
                # (e.g. an actor handed a borrowed ref back): cancel any
                # pending deferred release synchronously — before this
                # call returns the driver may rely on the object.
                head.unrelease_objects([ref.id.binary()])
            return count

        def unregister(oid):
            # Only a drop to zero releases cluster-wide: a second driver
            # handle to the same object (e.g. a deserialized copy) must
            # keep it alive.
            if original_unregister(oid):
                release_q.put(oid.binary())

        def release_loop():
            from ray_tpu._private.ids import ObjectID as _OID

            while not plumbing_stop.is_set():
                first = release_q.get()
                if first is None:
                    return  # shutdown sentinel
                batch = [first]
                time.sleep(0.05)
                while True:
                    try:
                        batch.append(release_q.get_nowait())
                    except _queue.Empty:
                        break
                # Level check at apply time: a handle re-acquired while
                # the release sat in this queue must win (the register
                # hook's synchronous unrelease covers the post-apply
                # window; this covers the pre-apply one).
                batch = [ob for ob in batch
                         if ob is not None
                         and worker.memory_store.local_ref_count(
                             _OID(ob)) == 0]
                try:
                    if batch:
                        head.release_objects(batch)
                except Exception:
                    pass

        worker.register_object_ref = register
        worker.unregister_object_ref = unregister
        t = threading.Thread(target=release_loop, daemon=True,
                             name="ray_tpu-release")
        t.start()

        def stop_cluster_plumbing():
            plumbing_stop.set()
            release_q.put(None)  # wake the blocking get
            with cond:
                cond.notify_all()
            dispatcher_thread.join(timeout=1.0)
            t.join(timeout=1.0)

        worker.stop_cluster_plumbing = stop_cluster_plumbing


class Cluster:
    """Reference: `ray.cluster_utils.Cluster` (`cluster_utils.py:99`)."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 shm_capacity: Optional[int] = None,
                 log_to_driver: bool = True):
        import os

        head_node_args = head_node_args or {}
        worker_mod.shutdown()
        self.driver_worker = worker_mod.init(
            num_cpus=head_node_args.get("num_cpus", 2),
            num_tpus=head_node_args.get("num_tpus"),
            resources=head_node_args.get("resources"))
        self.head = ClusterHead(self.driver_worker)
        backend = ClusterBackendMixin(self.driver_worker, self.head)
        self.driver_worker.backend = backend
        ClusterDriverMixin.install(self.driver_worker, self.head)
        self._wire_driver_spill_reports()
        # Node-wide shared object segment (plasma role): the head creates
        # it; node subprocesses attach by name. Large objects then cross
        # process boundaries zero-copy instead of via pickle RPC.
        self.shm_plane = None
        try:
            from ray_tpu._private import shm_plane as shm_mod

            kwargs = {"capacity": shm_capacity} if shm_capacity else {}
            self.shm_plane = shm_mod.SharedPlane(
                f"/ray_tpu_{os.getpid()}", create=True, **kwargs)
            self.shm_plane.install(self.driver_worker)
            port = self.shm_plane.store.start_transfer_server()
            # Advertise on the host nodes already use to reach the head's
            # RPC server — loopback in single-host simulation, the real
            # head host otherwise.
            self.head.transfer_addr = (self.head.server.address[0], port)
        except Exception:  # shm unavailable: pickle RPC still works
            self.shm_plane = None
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, str] = {}
        self._counter = 0
        # Driver log mirroring (reference log_monitor.py role): node
        # subprocess output re-prints here with a node prefix.
        self._log_monitor = None
        if log_to_driver:
            from ray_tpu._private.log_monitor import LogMonitor

            self._log_monitor = LogMonitor().start()

    @property
    def address(self) -> str:
        host, port = self.head.server.address
        return f"{host}:{port}"

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 wait: bool = True, simulate_remote_host: bool = False,
                 labels: Optional[Dict[str, str]] = None,
                 **_kw) -> str:
        """Spawn a node subprocess. With ``simulate_remote_host`` the node
        gets its own shm segment instead of attaching the head's, so the
        native transfer plane (cross-host path) is exercised on one
        machine — the reference's fake-multinode testing idea. The
        simulated node's own pulls force the TCP stream (its plane sets
        ``allow_local_pull=False``); pulls BY other processes FROM its
        segment may still take the same-host fast path, since the gate
        lives on the puller."""
        import os
        import tempfile

        self._counter += 1
        node_id = f"node-{self._counter}"
        cmd = [sys.executable, "-m", "ray_tpu._private.cluster_node",
               "--head", self.address, "--num-cpus", str(num_cpus),
               "--node-id", node_id]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        for key, value in (labels or {}).items():
            cmd += ["--label", f"{key}={value}"]
        if self.shm_plane is not None and not simulate_remote_host:
            cmd += ["--shm-name", self.shm_plane.name]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Node subprocesses must resolve ray_tpu the same way the driver
        # does (a driver using sys.path.insert — e.g. a checkout not on
        # PYTHONPATH — would otherwise spawn nodes that can't import us).
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + existing if existing else "")
        # Child output goes to a log file: a node that dies during
        # bring-up must leave evidence, not vanish silently.
        log_path = os.path.join(tempfile.gettempdir(),
                                f"ray_tpu_{os.getpid()}_{node_id}.log")
        log_f = open(log_path, "wb")
        proc = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=log_f)
        log_f.close()
        self._procs[node_id] = proc
        self._logs[node_id] = log_path
        # Dashboard log module reads these (reference: dashboard log
        # module serving per-node files).
        self.head.node_logs[node_id] = log_path
        if self._log_monitor is not None:
            self._log_monitor.add_file(node_id, log_path)
        if wait:
            # Generous deadline: imports alone can take tens of seconds
            # on a busy single-core box.
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if node_id in self.head.nodes:
                    return node_id
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node process exited with {proc.returncode};"
                        f" log tail:\n{self._log_tail(node_id)}")
                time.sleep(0.05)
            raise TimeoutError(
                f"node failed to register within 120s; log tail:\n"
                f"{self._log_tail(node_id)}")
        return node_id

    def _log_tail(self, node_id: str, nbytes: int = 4096) -> str:
        path = self._logs.get(node_id)
        if not path:
            return "<no log>"
        try:
            with open(path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode(errors="replace")
        except OSError as e:
            return f"<log unreadable: {e}>"

    def remove_node(self, node_id: str, graceful: bool = True):
        record = self.head.nodes.get(node_id)
        proc = self._procs.pop(node_id, None)
        if record is not None:
            if graceful:
                record.alive = False
                try:
                    RpcClient.to(record.address).call("shutdown")
                except Exception:
                    pass
            else:
                # Ungraceful removal is the fault-injection path (the
                # reference's NodeKiller): kill first, then run the full
                # death flow so in-flight work and actors recover.
                if proc is not None:
                    proc.kill()
                self.head.mark_node_dead(node_id, reason="killed")
            self.head.nodes.pop(node_id, None)
        if proc is not None:
            if not graceful:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def kill_node(self, node_id: str):
        """`kill -9` the node process *without* telling the head — death
        must be discovered by the health checker (chaos-test hook)."""
        proc = self._procs.get(node_id)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)

    def restart_head(self, mode: str = "graceful"):
        """Head (GCS) failover: tear the head's services down and bring
        a FRESH head up on the same address, recovering durable tables
        from gcs_storage (reference: GCS restart +
        `node_manager.proto:356` RayletNotifyGCSRestart).

        Two modes:

        - ``"graceful"`` (default): planned handoff — the old store's
          deferred group-commit batch is flushed before the swap, so
          the successor recovers EVERYTHING the old head accepted.
        - ``"crash"``: hard process death — NO flush; the sqlite
          connection drops with the open group-commit window
          uncommitted (WAL rolls it back). The documented loss bound is
          exactly that window (``gcs_commit_interval_s``): writes whose
          flush() returned (acked durable) survive, writes still
          riding the window may be lost, and nothing un-acked ever
          resurrects — the same contract raymc's ``gcs_durability`` /
          ``head_crash_recovery`` scenarios prove at small scope. Live
          nodes re-register through the report-returns-False path with
          no driver intervention; in-flight callers ride the fetch
          retry window to completion.

        What this simulates/recovers, and what it loses:
        - KV, named-actor, and placement-group tables reload from the
          configured ``gcs_storage_path`` (empty path = in-memory store
          → tables start empty, like the non-FT reference deployment).
        - The node table starts EMPTY; live node processes re-register
          through their resource-report loop (the report returns False
          for an unknown node → the node re-registers and re-reports
          its hosted actors and owned objects — the NotifyGCSRestart
          re-publish). Nodes that stay unreachable past the node-side
          suicide window exit themselves.
        - In-flight dispatch state (``inflight``) is lost: tasks already
          running on nodes complete and re-report their outputs after
          re-registration; callers keep waiting through the fetch
          retry window rather than getting spurious errors.
        - The driver process itself survives (the head is in-process
          here); in a real deployment driver death is a separate event.
        """
        if mode not in ("graceful", "crash"):
            raise ValueError(f"restart_head mode must be 'graceful' or "
                             f"'crash', got {mode!r}")
        old = self.head
        addr = old.server.address
        old.stop()
        old.server.shutdown()
        old_gcs = self.driver_worker.gcs
        if mode == "graceful":
            # Graceful handoff boundary: drain the old store's deferred
            # group-commit batch so the fresh GlobalState's new
            # connection recovers everything the old head accepted,
            # then close it (stops the flusher thread).
            flush = getattr(old_gcs, "flush_storage", None)
            if flush is not None:
                flush()
            close = getattr(old_gcs, "close_storage", None)
            if close is not None:
                close()
        else:
            # Hard crash: the connection dies with the group-commit
            # window open — sqlite rolls the pending transaction back,
            # exactly what a SIGKILL'd head process leaves behind.
            crash = getattr(old_gcs, "crash_storage", None)
            if crash is not None:
                crash()
        # Fresh GlobalState: prove recovery comes from durable storage,
        # not this process's memory.
        self.driver_worker.gcs = state_mod.GlobalState(self.driver_worker)
        new = ClusterHead(self.driver_worker, port=addr[1])
        new.transfer_addr = old.transfer_addr
        new.node_logs = dict(old.node_logs)
        # Recover placed-bundle locations from the durable PG table.
        for pg in self.driver_worker.gcs.placement_group_table().values():
            for i, nid in enumerate(getattr(pg, "bundle_nodes", None)
                                    or []):
                if nid is not None:
                    new.pg_bundle_nodes[(pg.id.binary(), i)] = nid
        self.head = new
        self.driver_worker.backend.head = new
        self.driver_worker.cluster_head = new
        self._wire_driver_spill_reports()
        new._ensure_health_checker()
        return new

    def _wire_driver_spill_reports(self):
        """Driver-local spills feed the (current) head's spill-URL
        directory the same way node spills do over RPC."""
        store = self.driver_worker.memory_store
        cluster = self

        def on_spilled(oid, url):
            try:
                cluster.head.note_spilled(oid.binary(), url)
            except Exception:
                pass

        store.on_spilled = on_spilled

    def nodes(self) -> List[dict]:
        return self.head._get_nodes()

    def shutdown(self):
        # Drain the group-committed submit channels BEFORE tearing nodes
        # down: a batch parked in a CoalescingBatcher or an un-acked
        # pipelined request is an accepted submission, and the shutdown
        # boundary is exactly where a non-draining close would lose it.
        backend = getattr(self.driver_worker, "backend", None)
        if isinstance(backend, ClusterBackendMixin):
            backend.drain_channels(timeout=2.0)
        self.head.stop()
        for node_id in list(self._procs):
            self.remove_node(node_id)
        if self._log_monitor is not None:
            self._log_monitor.stop()  # final drain catches exit output
            self._log_monitor = None
        self.head.server.shutdown()
        if self.shm_plane is not None:
            # Detach from the worker first (new fetches skip shm), then
            # unlink WITHOUT unmapping: a fetch thread mid-read keeps a
            # valid mapping instead of segfaulting on teardown.
            if getattr(self.driver_worker, "shm_plane", None) \
                    is self.shm_plane:
                self.driver_worker.shm_plane = None
            self.shm_plane.destroy(unmap=False)
            self.shm_plane = None
        worker_mod.shutdown()
