"""Cluster mode: multiprocess nodes on one machine (or many).

Reference: `python/ray/cluster_utils.py:99` — `Cluster` runs N
raylet-equivalents as separate OS processes, which is how the reference
tests multi-node scheduling and failure handling without real machines
(SURVEY.md §4). Here:

- the driver process is the head: it hosts the GCS-style services
  (node table, object directory) and its own LocalBackend;
- `add_node()` spawns `ray_tpu._private.cluster_node` subprocesses that
  register and execute shipped tasks;
- scheduling: local-first pack, spill to the least-loaded remote node
  with capacity (the reference's hybrid policy shape);
- objects stay with their executing node (owner-based directory); gets
  pull node→node.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.task_spec import TaskKind


class _NodeRecord:
    def __init__(self, node_id: str, address: Tuple[str, int],
                 resources: Dict[str, float]):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources = resources
        self.alive = True


class ClusterHead:
    """GCS-equivalent services hosted in the driver process."""

    def __init__(self, worker):
        self.worker = worker
        self._lock = threading.Lock()
        self.nodes: Dict[str, _NodeRecord] = {}
        self.object_locations: Dict[bytes, Tuple[str, int]] = {}
        self.actor_nodes: Dict[bytes, str] = {}
        self.server = RpcServer({
            "register_node": self._register_node,
            "report_objects": self._report_objects,
            "locate": self._locate,
            "get_object": self._get_object,
            "get_nodes": self._get_nodes,
        })

    def _register_node(self, node_id, address, resources):
        with self._lock:
            self.nodes[node_id] = _NodeRecord(node_id, address, resources)
        return True

    def _report_objects(self, oids: List[bytes], address):
        with self._lock:
            for oid in oids:
                self.object_locations[oid] = tuple(address)
        return True

    def _locate(self, oid: bytes):
        with self._lock:
            loc = self.object_locations.get(oid)
        if loc is not None:
            return loc
        # The driver itself may own it.
        if self.worker.memory_store.contains(ObjectID(oid)):
            return self.server.address
        return None

    def _get_object(self, oid: bytes, timeout: float = 30.0):
        object_id = ObjectID(oid)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, value, error = self.worker.memory_store.peek(object_id)
            if ready:
                return True, value, error
            time.sleep(0.005)
        return False, None, None

    def _get_nodes(self):
        with self._lock:
            return [
                {"NodeID": n.node_id, "Address": n.address,
                 "Resources": n.resources, "Alive": n.alive}
                for n in self.nodes.values()
            ]


class ClusterBackendMixin:
    """Installed over the driver's LocalBackend: route specs to nodes."""

    def __init__(self, worker, head: ClusterHead):
        self.worker = worker
        self.head = head
        self.local_backend = worker.backend
        self._rr = 0

    def submit(self, spec) -> None:
        head = self.head
        if spec.kind == TaskKind.ACTOR_TASK:
            node_id = head.actor_nodes.get(spec.actor_id.binary())
            if node_id is not None:
                self._send(head.nodes[node_id], spec)
                return
            self._ensure_local_deps(spec)
            self.local_backend.submit(spec)
            return
        target = self._choose_node(spec)
        if target is None:
            # A head-local task may still depend on remote objects.
            self._ensure_local_deps(spec)
            self.local_backend.submit(spec)
            return
        if spec.kind == TaskKind.ACTOR_CREATION:
            head.actor_nodes[spec.actor_id.binary()] = target.node_id
        self._send(target, spec)

    def _ensure_local_deps(self, spec):
        from ray_tpu.object_ref import ObjectRef

        store = self.worker.memory_store
        head = self.head
        missing = [a.id for a in
                   list(spec.args) + list(spec.kwargs.values())
                   if isinstance(a, ObjectRef) and not store.contains(a.id)]
        for oid in missing:
            def fetch(oid=oid):
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if store.contains(oid):
                        return
                    loc = head._locate(oid.binary())
                    if loc is not None and \
                            tuple(loc) != head.server.address:
                        ok, value, err = RpcClient.to(tuple(loc)).call(
                            "get_object", oid=oid.binary())
                        if ok:
                            store.put(oid, value, error=err)
                            return
                    time.sleep(0.01)

            threading.Thread(target=fetch, daemon=True).start()

    def _choose_node(self, spec) -> Optional[_NodeRecord]:
        """Local-first pack; spill to remote capacity when local can't run
        it now (reference hybrid policy shape)."""
        from ray_tpu._private.resources import to_milli

        request = to_milli(spec.resources)
        local = self.local_backend.resources
        pending = self.local_backend.pending_demand_milli()
        with local._cond:
            local_fits_now = all(
                local._available.get(k, 0) - pending.get(k, 0) >= v
                for k, v in request.items())
        if local_fits_now:
            return None
        candidates = [n for n in self.head.nodes.values() if n.alive]
        best, best_avail = None, -1.0
        for node in candidates:
            try:
                info = RpcClient.to(node.address).call("ping")
            except Exception:
                node.alive = False
                continue
            avail = info["available"]
            if all(avail.get(k, 0) * 1000 >= v
                   for k, v in request.items()):
                score = sum(avail.values())
                if score > best_avail:
                    best, best_avail = node, score
        return best

    def _send(self, node: _NodeRecord, spec):
        # Proactively publish local args so the node can pull them.
        from ray_tpu.object_ref import ObjectRef

        local_oids = []
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(arg, ObjectRef) and \
                    self.worker.memory_store.contains(arg.id):
                local_oids.append(arg.id.binary())
        if local_oids:
            self.head._report_objects(local_oids, self.head.server.address)
        RpcClient.to(node.address).call("submit_task", spec=spec)

    # Delegate everything else to the local backend.

    def __getattr__(self, name):
        return getattr(self.local_backend, name)


class ClusterDriverMixin:
    """get()/wait() that pull remote objects on demand."""

    @staticmethod
    def install(worker, head: ClusterHead):
        worker.cluster_head = head
        original_get = worker.get_objects
        original_wait = worker.wait
        fetching: set = set()
        lock = threading.Lock()

        def ensure_fetch(ref):
            if worker.memory_store.contains(ref.id):
                return
            key = ref.id.binary()
            with lock:
                if key in fetching:
                    return
                fetching.add(key)

            def fetch():
                try:
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline:
                        loc = head._locate(key)
                        if loc is not None and \
                                tuple(loc) != head.server.address:
                            ok, value, err = RpcClient.to(
                                tuple(loc)).call("get_object", oid=key)
                            if ok:
                                worker.memory_store.put(ref.id, value,
                                                        error=err)
                                return
                        if worker.memory_store.contains(ref.id):
                            return
                        time.sleep(0.01)
                finally:
                    with lock:
                        fetching.discard(key)

            threading.Thread(target=fetch, daemon=True).start()

        def get_objects(refs, timeout=None):
            for ref in refs:
                ensure_fetch(ref)
            return original_get(refs, timeout)

        def wait(refs, num_returns, timeout, fetch_local=True):
            for ref in refs:
                ensure_fetch(ref)
            return original_wait(refs, num_returns, timeout, fetch_local)

        worker.get_objects = get_objects
        worker.wait = wait


class Cluster:
    """Reference: `ray.cluster_utils.Cluster` (`cluster_utils.py:99`)."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        head_node_args = head_node_args or {}
        worker_mod.shutdown()
        self.driver_worker = worker_mod.init(
            num_cpus=head_node_args.get("num_cpus", 2),
            num_tpus=head_node_args.get("num_tpus"),
            resources=head_node_args.get("resources"))
        self.head = ClusterHead(self.driver_worker)
        backend = ClusterBackendMixin(self.driver_worker, self.head)
        self.driver_worker.backend = backend
        ClusterDriverMixin.install(self.driver_worker, self.head)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._counter = 0

    @property
    def address(self) -> str:
        host, port = self.head.server.address
        return f"{host}:{port}"

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 wait: bool = True, **_kw) -> str:
        self._counter += 1
        node_id = f"node-{self._counter}"
        cmd = [sys.executable, "-m", "ray_tpu._private.cluster_node",
               "--head", self.address, "--num-cpus", str(num_cpus),
               "--node-id", node_id]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        import os

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(cmd, env=env)
        self._procs[node_id] = proc
        if wait:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if node_id in self.head.nodes:
                    return node_id
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node process exited with {proc.returncode}")
                time.sleep(0.05)
            raise TimeoutError("node failed to register")
        return node_id

    def remove_node(self, node_id: str, graceful: bool = True):
        record = self.head.nodes.get(node_id)
        proc = self._procs.pop(node_id, None)
        if record is not None:
            record.alive = False
            if graceful:
                try:
                    RpcClient.to(record.address).call("shutdown")
                except Exception:
                    pass
            self.head.nodes.pop(node_id, None)
        if proc is not None:
            if not graceful:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def nodes(self) -> List[dict]:
        return self.head._get_nodes()

    def shutdown(self):
        for node_id in list(self._procs):
            self.remove_node(node_id)
        self.head.server.shutdown()
        worker_mod.shutdown()
