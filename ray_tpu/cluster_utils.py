"""Cluster mode: multiprocess nodes on one machine (or many).

Reference: `python/ray/cluster_utils.py:99` — `Cluster` runs N
raylet-equivalents as separate OS processes, which is how the reference
tests multi-node scheduling and failure handling without real machines
(SURVEY.md §4). Here:

- the driver process is the head: it hosts the GCS-style services
  (node table, object directory) and its own LocalBackend;
- `add_node()` spawns `ray_tpu._private.cluster_node` subprocesses that
  register and execute shipped tasks;
- scheduling: local-first pack, spill to the least-loaded remote node
  with capacity (the reference's hybrid policy shape);
- objects stay with their executing node (owner-based directory); gets
  pull node→node.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.task_spec import TaskKind
from ray_tpu.exceptions import ActorDiedError, OwnerDiedError


def _try_shm_fetch(worker, oid) -> bool:
    """Zero-copy read from the node's shared segment, if the object is
    there. Faster and cheaper than any RPC — always tried first."""
    plane = getattr(worker, "shm_plane", None)
    if plane is None:
        return False
    try:
        found, value = plane.get(oid)
    except Exception:
        return False
    if not found:
        return False
    worker.memory_store.put(oid, value)
    return True


def _try_transfer_fetch(worker, oid, loc_info) -> bool:
    """Chunked native pull from the owner's transfer server into the
    local segment, then zero-copy read — the cross-host object plane
    (reference: ObjectManager Pull, `pull_manager.h:52`). Skipped when
    the owner shares our segment (plain shm read suffices) or the
    object isn't shm-backed."""
    plane = getattr(worker, "shm_plane", None)
    if plane is None or not loc_info:
        return False
    transfer = loc_info.get("transfer")
    if transfer is None or loc_info.get("shm") == plane.name:
        return False
    try:
        rc = plane.store.pull_from(oid.binary(), transfer[0], transfer[1])
        if rc not in (0, -5):
            return False
        return _try_shm_fetch(worker, oid)
    except Exception:
        return False


class _NodeRecord:
    def __init__(self, node_id: str, address: Tuple[str, int],
                 resources: Dict[str, float],
                 transfer: Optional[Tuple[str, int]] = None,
                 shm_name: Optional[str] = None):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources = resources
        self.alive = True
        # Object-plane endpoints: the native transfer server serving this
        # node's shm segment, and the segment name (nodes sharing a
        # segment read each other's objects without any transfer).
        self.transfer = tuple(transfer) if transfer else None
        self.shm_name = shm_name


class ClusterHead:
    """GCS-equivalent services hosted in the driver process."""

    def __init__(self, worker):
        self.worker = worker
        self._lock = threading.Lock()
        self.nodes: Dict[str, _NodeRecord] = {}
        self.object_locations: Dict[bytes, Tuple[str, int]] = {}
        self.actor_nodes: Dict[bytes, str] = {}
        self.server = RpcServer({
            "register_node": self._register_node,
            "report_objects": self._report_objects,
            "locate": self._locate,
            "locate2": self._locate2,
            "get_object": self._get_object,
            "get_nodes": self._get_nodes,
        })
        self.transfer_addr: Optional[Tuple[str, int]] = None

    def _register_node(self, node_id, address, resources,
                       transfer=None, shm_name=None):
        with self._lock:
            self.nodes[node_id] = _NodeRecord(node_id, address, resources,
                                              transfer, shm_name)
        return True

    def _report_objects(self, oids: List[bytes], address):
        with self._lock:
            for oid in oids:
                self.object_locations[oid] = tuple(address)
        return True

    def _locate(self, oid: bytes):
        """Owner's RPC address, or None. (Legacy callers; see _locate2.)"""
        info = self._locate2(oid)
        return info["address"] if info else None

    def _locate2(self, oid: bytes):
        """Rich location: {"address", "transfer", "shm"} of the owner."""
        with self._lock:
            loc = self.object_locations.get(oid)
            if loc is not None:
                for n in self.nodes.values():
                    if n.address == loc:
                        return {"address": loc, "transfer": n.transfer,
                                "shm": n.shm_name}
                if loc == self.server.address:
                    return self._self_location()
                return {"address": loc, "transfer": None, "shm": None}
        if self.worker.memory_store.contains(ObjectID(oid)):
            return self._self_location()
        return None

    def _self_location(self):
        plane = getattr(self.worker, "shm_plane", None)
        return {"address": self.server.address,
                "transfer": getattr(self, "transfer_addr", None),
                "shm": plane.name if plane else None}

    def _get_object(self, oid: bytes, timeout: float = 30.0):
        object_id = ObjectID(oid)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, value, error = self.worker.memory_store.peek(object_id)
            if ready:
                return True, value, error
            time.sleep(0.005)
        return False, None, None

    def _get_nodes(self):
        with self._lock:
            return [
                {"NodeID": n.node_id, "Address": n.address,
                 "Resources": n.resources, "Alive": n.alive}
                for n in self.nodes.values()
            ]


class ClusterBackendMixin:
    """Installed over the driver's LocalBackend: route specs to nodes."""

    def __init__(self, worker, head: ClusterHead):
        self.worker = worker
        self.head = head
        self.local_backend = worker.backend
        self._rr = 0

    def submit(self, spec) -> None:
        head = self.head
        if spec.kind == TaskKind.ACTOR_TASK:
            node_id = head.actor_nodes.get(spec.actor_id.binary())
            if node_id is not None:
                actor_desc = spec.actor_id.hex()[:8]
                record = head.nodes.get(node_id)
                if record is None or not record.alive:
                    self._fail_spec(spec, ActorDiedError(
                        actor_desc, f"its node {node_id} is dead"))
                    return
                try:
                    self._send(record, spec)
                except (ConnectionError, OSError) as e:
                    # Transport failure: the node itself is unreachable.
                    record.alive = False
                    self._fail_spec(spec, ActorDiedError(
                        actor_desc, f"node {node_id} unreachable: {e}"))
                except Exception as e:
                    # Handler-level error: the node is healthy, this
                    # submission failed — fail the task, keep the node.
                    self._fail_spec(spec, e)
                return
            self._ensure_local_deps(spec)
            self.local_backend.submit(spec)
            return
        target = self._choose_node(spec)
        if target is None:
            # A head-local task may still depend on remote objects.
            self._ensure_local_deps(spec)
            self.local_backend.submit(spec)
            return
        if spec.kind == TaskKind.ACTOR_CREATION:
            head.actor_nodes[spec.actor_id.binary()] = target.node_id
        self._send(target, spec)

    def _fail_spec(self, spec, error: Exception) -> None:
        store = self.worker.memory_store
        for oid in spec.return_ids:
            store.put(oid, None, error=error)

    def _ensure_local_deps(self, spec):
        from ray_tpu.object_ref import ObjectRef

        store = self.worker.memory_store
        head = self.head
        missing = [a.id for a in
                   list(spec.args) + list(spec.kwargs.values())
                   if isinstance(a, ObjectRef) and not store.contains(a.id)]
        for oid in missing:
            def fetch(oid=oid):
                if _try_shm_fetch(self.worker, oid):
                    return
                # Transport failures are retried until the deadline (a
                # brief owner stall must not poison the object); if the
                # owner stayed unreachable the whole window, `get` raises
                # OwnerDiedError instead of hanging. A never-located
                # object is left pending — its producer may just be slow.
                deadline = time.monotonic() + 60
                transport_err = None
                while time.monotonic() < deadline:
                    if store.contains(oid):
                        return
                    info = head._locate2(oid.binary())
                    if info is not None and \
                            tuple(info["address"]) != head.server.address:
                        if _try_transfer_fetch(self.worker, oid, info):
                            return
                        try:
                            ok, value, err = RpcClient.to(
                                tuple(info["address"])).call(
                                "get_object", oid=oid.binary())
                        except Exception as e:
                            transport_err = e
                            time.sleep(0.2)
                            continue
                        if ok:
                            store.put(oid, value, error=err)
                            return
                    time.sleep(0.01)
                if transport_err is not None and not store.contains(oid):
                    store.put(oid, None, error=OwnerDiedError(
                        oid.hex()[:12],
                        f"owner of {oid.hex()[:12]} unreachable for 60s: "
                        f"{transport_err}"))

            threading.Thread(target=fetch, daemon=True).start()

    def _choose_node(self, spec) -> Optional[_NodeRecord]:
        """Local-first pack; spill to remote capacity when local can't run
        it now (reference hybrid policy shape)."""
        from ray_tpu._private.resources import to_milli

        request = to_milli(spec.resources)
        local = self.local_backend.resources
        pending = self.local_backend.pending_demand_milli()
        with local._cond:
            local_fits_now = all(
                local._available.get(k, 0) - pending.get(k, 0) >= v
                for k, v in request.items())
        if local_fits_now:
            return None
        candidates = [n for n in self.head.nodes.values() if n.alive]
        best, best_avail = None, -1.0
        for node in candidates:
            try:
                info = RpcClient.to(node.address).call("ping")
            except Exception:
                node.alive = False
                continue
            avail = info["available"]
            if all(avail.get(k, 0) * 1000 >= v
                   for k, v in request.items()):
                score = sum(avail.values())
                if score > best_avail:
                    best, best_avail = node, score
        return best

    def _send(self, node: _NodeRecord, spec):
        # Proactively publish local args so the node can pull them.
        from ray_tpu.object_ref import ObjectRef

        local_oids = []
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(arg, ObjectRef) and \
                    self.worker.memory_store.contains(arg.id):
                local_oids.append(arg.id.binary())
        if local_oids:
            self.head._report_objects(local_oids, self.head.server.address)
        RpcClient.to(node.address).call("submit_task", spec=spec)

    # Delegate everything else to the local backend.

    def __getattr__(self, name):
        return getattr(self.local_backend, name)


class ClusterDriverMixin:
    """get()/wait() that pull remote objects on demand."""

    @staticmethod
    def install(worker, head: ClusterHead):
        worker.cluster_head = head
        original_get = worker.get_objects
        original_wait = worker.wait
        fetching: set = set()
        lock = threading.Lock()

        def ensure_fetch(ref):
            if worker.memory_store.contains(ref.id):
                return
            key = ref.id.binary()
            with lock:
                if key in fetching:
                    return
                fetching.add(key)

            def fetch():
                try:
                    deadline = time.monotonic() + 60
                    transport_err = None
                    while time.monotonic() < deadline:
                        if _try_shm_fetch(worker, ref.id):
                            return
                        info = head._locate2(key)
                        if info is not None and \
                                tuple(info["address"]) != \
                                head.server.address:
                            if _try_transfer_fetch(worker, ref.id, info):
                                return
                            try:
                                ok, value, err = RpcClient.to(
                                    tuple(info["address"])).call(
                                    "get_object", oid=key)
                            except Exception as e:
                                transport_err = e
                                time.sleep(0.2)
                                continue
                            if ok:
                                worker.memory_store.put(ref.id, value,
                                                        error=err)
                                return
                        if worker.memory_store.contains(ref.id):
                            return
                        time.sleep(0.01)
                    if transport_err is not None and \
                            not worker.memory_store.contains(ref.id):
                        worker.memory_store.put(
                            ref.id, None, error=OwnerDiedError(
                                ref.id.hex()[:12],
                                f"owner unreachable for 60s: "
                                f"{transport_err}"))
                finally:
                    with lock:
                        fetching.discard(key)

            threading.Thread(target=fetch, daemon=True).start()

        def get_objects(refs, timeout=None):
            for ref in refs:
                ensure_fetch(ref)
            return original_get(refs, timeout)

        def wait(refs, num_returns, timeout, fetch_local=True):
            for ref in refs:
                ensure_fetch(ref)
            return original_wait(refs, num_returns, timeout, fetch_local)

        worker.get_objects = get_objects
        worker.wait = wait


class Cluster:
    """Reference: `ray.cluster_utils.Cluster` (`cluster_utils.py:99`)."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 shm_capacity: Optional[int] = None):
        import os

        head_node_args = head_node_args or {}
        worker_mod.shutdown()
        self.driver_worker = worker_mod.init(
            num_cpus=head_node_args.get("num_cpus", 2),
            num_tpus=head_node_args.get("num_tpus"),
            resources=head_node_args.get("resources"))
        self.head = ClusterHead(self.driver_worker)
        backend = ClusterBackendMixin(self.driver_worker, self.head)
        self.driver_worker.backend = backend
        ClusterDriverMixin.install(self.driver_worker, self.head)
        # Node-wide shared object segment (plasma role): the head creates
        # it; node subprocesses attach by name. Large objects then cross
        # process boundaries zero-copy instead of via pickle RPC.
        self.shm_plane = None
        try:
            from ray_tpu._private import shm_plane as shm_mod

            kwargs = {"capacity": shm_capacity} if shm_capacity else {}
            self.shm_plane = shm_mod.SharedPlane(
                f"/ray_tpu_{os.getpid()}", create=True, **kwargs)
            self.shm_plane.install(self.driver_worker)
            port = self.shm_plane.store.start_transfer_server()
            # Advertise on the host nodes already use to reach the head's
            # RPC server — loopback in single-host simulation, the real
            # head host otherwise.
            self.head.transfer_addr = (self.head.server.address[0], port)
        except Exception:  # shm unavailable: pickle RPC still works
            self.shm_plane = None
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, str] = {}
        self._counter = 0

    @property
    def address(self) -> str:
        host, port = self.head.server.address
        return f"{host}:{port}"

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 wait: bool = True, simulate_remote_host: bool = False,
                 **_kw) -> str:
        """Spawn a node subprocess. With ``simulate_remote_host`` the node
        gets its own shm segment instead of attaching the head's, so the
        native transfer plane (cross-host path) is exercised on one
        machine — the reference's fake-multinode testing idea."""
        import os
        import tempfile

        self._counter += 1
        node_id = f"node-{self._counter}"
        cmd = [sys.executable, "-m", "ray_tpu._private.cluster_node",
               "--head", self.address, "--num-cpus", str(num_cpus),
               "--node-id", node_id]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        if self.shm_plane is not None and not simulate_remote_host:
            cmd += ["--shm-name", self.shm_plane.name]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Child output goes to a log file: a node that dies during
        # bring-up must leave evidence, not vanish silently.
        log_path = os.path.join(tempfile.gettempdir(),
                                f"ray_tpu_{os.getpid()}_{node_id}.log")
        log_f = open(log_path, "wb")
        proc = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=log_f)
        log_f.close()
        self._procs[node_id] = proc
        self._logs[node_id] = log_path
        if wait:
            # Generous deadline: imports alone can take tens of seconds
            # on a busy single-core box.
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if node_id in self.head.nodes:
                    return node_id
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node process exited with {proc.returncode};"
                        f" log tail:\n{self._log_tail(node_id)}")
                time.sleep(0.05)
            raise TimeoutError(
                f"node failed to register within 120s; log tail:\n"
                f"{self._log_tail(node_id)}")
        return node_id

    def _log_tail(self, node_id: str, nbytes: int = 4096) -> str:
        path = self._logs.get(node_id)
        if not path:
            return "<no log>"
        try:
            with open(path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode(errors="replace")
        except OSError as e:
            return f"<log unreadable: {e}>"

    def remove_node(self, node_id: str, graceful: bool = True):
        record = self.head.nodes.get(node_id)
        proc = self._procs.pop(node_id, None)
        if record is not None:
            record.alive = False
            if graceful:
                try:
                    RpcClient.to(record.address).call("shutdown")
                except Exception:
                    pass
            self.head.nodes.pop(node_id, None)
        if proc is not None:
            if not graceful:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def nodes(self) -> List[dict]:
        return self.head._get_nodes()

    def shutdown(self):
        for node_id in list(self._procs):
            self.remove_node(node_id)
        self.head.server.shutdown()
        if self.shm_plane is not None:
            self.shm_plane.destroy()
            self.shm_plane = None
        worker_mod.shutdown()
