"""ray_tpu.workflow: durable DAG execution.

Reference: `python/ray/workflow/` (SURVEY.md §2.4) — `workflow.run(dag)`
executes a `ray_tpu.dag` graph with per-step results checkpointed to
storage (`workflow_storage.py` equivalent), so a crashed workflow resumes
from completed steps. Management surface (reference `workflow_access.py`
WorkflowManagementActor): a named detached actor exposing
list/status/cancel/resume to any driver. Events (reference
`event_listener.py` / `http_event_provider.py`): `wait_for_event` steps
block durably until `trigger_event` delivers a payload; `TimerListener`
fires at a wall-clock time. Per-step `max_retries`/`catch_exceptions`
via `with_options` (reference `workflow.options`).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, InputNode

_storage_root: Optional[str] = None
_lock = threading.Lock()


def init(storage: Optional[str] = None):
    """Set the durable storage root (default ~/.ray_tpu_workflows)."""
    global _storage_root
    _storage_root = storage or os.path.expanduser("~/.ray_tpu_workflows")
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    if _storage_root is None:
        init()
    return _storage_root


class WorkflowCancelledError(RuntimeError):
    pass


class WorkflowStorage:
    """Filesystem-backed step-result store (reference:
    `workflow/workflow_storage.py`)."""

    def __init__(self, workflow_id: str):
        self.path = os.path.join(_root(), workflow_id)
        os.makedirs(os.path.join(self.path, "steps"), exist_ok=True)
        os.makedirs(os.path.join(self.path, "events"), exist_ok=True)

    # cancellation flag (written by any process, read between steps)
    def request_cancel(self):
        with open(os.path.join(self.path, "cancel"), "w") as f:
            f.write("1")

    def cancel_requested(self) -> bool:
        return os.path.exists(os.path.join(self.path, "cancel"))

    # the DAG itself, so resume works without the original driver
    # (cloudpickle: step functions are usually closures/locals)
    def save_dag(self, dag, dag_input):
        import cloudpickle

        tmp = os.path.join(self.path, "dag.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump((dag, dag_input), f)
        os.replace(tmp, os.path.join(self.path, "dag.pkl"))

    def load_dag(self):
        with open(os.path.join(self.path, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def has_dag(self) -> bool:
        return os.path.exists(os.path.join(self.path, "dag.pkl"))

    # events
    def event_file(self, key: str) -> str:
        return os.path.join(self.path, "events", f"{key}.pkl")

    def post_event(self, key: str, payload):
        tmp = self.event_file(key) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, self.event_file(key))

    def get_event(self, key: str):
        with open(self.event_file(key), "rb") as f:
            return pickle.load(f)

    def has_event(self, key: str) -> bool:
        return os.path.exists(self.event_file(key))

    def _step_file(self, step_id: str) -> str:
        return os.path.join(self.path, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_file(step_id))

    def load_step(self, step_id: str):
        with open(self._step_file(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, value):
        # cloudpickle: step values may hold rich exception objects
        # (catch_exceptions) or closures; loading stays stdlib pickle
        # (cloudpickle output is pickle-compatible).
        import cloudpickle

        tmp = self._step_file(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._step_file(step_id))

    def set_status(self, status: str, error: str = ""):
        with open(os.path.join(self.path, "status"), "w") as f:
            f.write(f"{status}\n{error}")

    def get_status(self) -> str:
        try:
            with open(os.path.join(self.path, "status")) as f:
                return f.read().splitlines()[0]
        except OSError:
            return "NONE"


def _step_id_of(node: DAGNode) -> str:
    """Deterministic step id: structural position + function name."""
    name = getattr(getattr(node, "_fn", None), "__name__", None) or \
        type(node).__name__
    return f"{name}-{_structural_hash(node)[:12]}"


def _structural_hash(node: DAGNode, seen=None) -> str:
    seen = seen or {}
    if id(node) in seen:
        return seen[id(node)]
    parts = [type(node).__name__,
             getattr(getattr(node, "_fn", None), "__name__", "")]
    for a in node._bound_args:
        parts.append(_structural_hash(a, seen) if isinstance(a, DAGNode)
                     else repr(a))
    for k, v in sorted(node._bound_kwargs.items()):
        parts.append(k)
        parts.append(_structural_hash(v, seen) if isinstance(v, DAGNode)
                     else repr(v))
    h = hashlib.sha1("|".join(parts).encode()).hexdigest()
    seen[id(node)] = h
    return h


def _execute_durable(node: DAGNode, storage: WorkflowStorage, dag_input,
                     cache: Dict[str, Any]):
    if node._uuid in cache:
        return cache[node._uuid]
    if storage.cancel_requested():
        raise WorkflowCancelledError(
            f"workflow cancelled ({os.path.basename(storage.path)})")
    if isinstance(node, InputNode):
        result = dag_input
    elif isinstance(node, EventNode):
        step_id = f"event-{node._key}"
        if storage.has_step(step_id):
            result = storage.load_step(step_id)
        else:
            result = node._listener.poll_for_event(storage)
            storage.save_step(step_id, result)
    else:
        step_id = _step_id_of(node)
        if storage.has_step(step_id):
            result = storage.load_step(step_id)
        else:
            # Resolve children durably first, then run this step.
            args = tuple(
                _execute_durable(a, storage, dag_input, cache)
                if isinstance(a, DAGNode) else a
                for a in node._bound_args)
            kwargs = {
                k: _execute_durable(v, storage, dag_input, cache)
                if isinstance(v, DAGNode) else v
                for k, v in node._bound_kwargs.items()}
            fn = getattr(node, "_fn", None)
            if fn is None:
                raise TypeError(
                    f"workflow steps must be function nodes, got "
                    f"{type(node).__name__}")
            # Re-check here: the entry check above runs during the
            # initial DAG descent (t~0 for every node); by the time the
            # dependencies have executed, a cancel may have arrived.
            if storage.cancel_requested():
                raise WorkflowCancelledError(
                    f"workflow cancelled "
                    f"({os.path.basename(storage.path)})")
            opts = getattr(node, "_workflow_options", {})
            retries = int(opts.get("max_retries", 0))
            catch = bool(opts.get("catch_exceptions", False))
            attempt = 0
            while True:
                try:
                    result = ray_tpu.get(fn.remote(*args, **kwargs))
                    if catch:
                        result = (result, None)
                    break
                except WorkflowCancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    if attempt < retries:
                        attempt += 1
                        continue
                    if catch:
                        result = (None, e)
                        break
                    raise
            storage.save_step(step_id, result)
    cache[node._uuid] = result
    return result


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        dag_input: Any = None) -> Any:
    """Run (or resume) a workflow to completion, returning the output.
    Completed steps are skipped on resume."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag, dag_input)  # resume needs no original driver
    storage.set_status("RUNNING")
    try:
        result = _execute_durable(dag, storage, dag_input, {})
        storage.save_step("__output__", result)
        storage.set_status("SUCCESSFUL")
        return result
    except WorkflowCancelledError as e:
        storage.set_status("CANCELED", str(e))
        raise
    except BaseException as e:  # noqa: BLE001
        storage.set_status("FAILED", str(e))
        raise


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              dag_input: Any = None):
    """Launch as a task; returns an ObjectRef of the output."""

    @ray_tpu.remote
    def _runner(payload):
        dag, wid, dinput = payload
        return run(dag, workflow_id=wid, dag_input=dinput)

    return _runner.remote((dag, workflow_id, dag_input))


def get_status(workflow_id: str) -> str:
    return WorkflowStorage(workflow_id).get_status()


def get_output(workflow_id: str):
    storage = WorkflowStorage(workflow_id)
    if not storage.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id} has no stored output")
    return storage.load_step("__output__")


def resume(workflow_id: str):
    """Resume a FAILED/CANCELED/RUNNING-at-crash workflow from its stored
    DAG and completed steps; returns the output. Already-successful
    workflows return their stored output directly."""
    storage = WorkflowStorage(workflow_id)
    if storage.get_status() == "SUCCESSFUL":
        return storage.load_step("__output__")
    if not storage.has_dag():
        raise ValueError(
            f"workflow {workflow_id} has no stored DAG (pre-upgrade run?);"
            " re-issue run(dag, workflow_id=...) to resume")
    # Clear a stale cancel flag so the resumed run can proceed.
    cancel_path = os.path.join(storage.path, "cancel")
    if os.path.exists(cancel_path):
        os.remove(cancel_path)
    dag, dag_input = storage.load_dag()
    return run(dag, workflow_id=workflow_id, dag_input=dag_input)


def resume_all() -> List[tuple]:
    """Resume every workflow not already successful (reference:
    `workflow.resume_all` after cluster restart). Returns
    [(workflow_id, output), ...] for the resumed ones."""
    out = []
    for wid, status in list_all():
        if status in ("FAILED", "CANCELED", "RUNNING") \
                and WorkflowStorage(wid).has_dag():
            try:
                out.append((wid, resume(wid)))
            except Exception:  # noqa: BLE001 — keep resuming the rest
                pass
    return out


def cancel(workflow_id: str):
    """Request cancellation; takes effect at the next step boundary."""
    WorkflowStorage(workflow_id).request_cancel()


def list_all() -> List[tuple]:
    root = _root()
    out = []
    for wid in sorted(os.listdir(root)):
        if os.path.isdir(os.path.join(root, wid)):
            out.append((wid, WorkflowStorage(wid).get_status()))
    return out


def with_options(node: DAGNode, *, max_retries: int = 0,
                 catch_exceptions: bool = False) -> DAGNode:
    """Attach per-step execution options (reference `workflow.options`):
    `max_retries` re-runs a failing step; `catch_exceptions` makes the
    step yield `(result, None)` or `(None, exception)` instead of
    raising."""
    node._workflow_options = {"max_retries": max_retries,
                              "catch_exceptions": catch_exceptions}
    return node


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------


class EventListener:
    """Reference `workflow/event_listener.py`: poll_for_event blocks until
    the external event arrives, returning its payload. Durable: once a
    wait_for_event step commits, resume never re-waits."""

    def poll_for_event(self, storage: WorkflowStorage):
        raise NotImplementedError


class TriggerListener(EventListener):
    """Waits for `trigger_event(workflow_id, key, payload)`."""

    def __init__(self, key: str, poll_interval_s: float = 0.05,
                 timeout_s: Optional[float] = None):
        self.key = key
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s

    def poll_for_event(self, storage: WorkflowStorage):
        deadline = None if self.timeout_s is None \
            else time.monotonic() + self.timeout_s
        while not storage.has_event(self.key):
            if storage.cancel_requested():
                raise WorkflowCancelledError("cancelled while waiting "
                                             f"for event {self.key!r}")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"event {self.key!r} not delivered in "
                    f"{self.timeout_s}s")
            time.sleep(self.poll_interval_s)
        return storage.get_event(self.key)


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference TimerListener)."""

    def __init__(self, fire_at: float):
        self.fire_at = fire_at

    def poll_for_event(self, storage: WorkflowStorage):
        while time.time() < self.fire_at:
            if storage.cancel_requested():
                raise WorkflowCancelledError("cancelled in timer wait")
            time.sleep(min(0.05, max(0.0, self.fire_at - time.time())))
        return self.fire_at


class EventNode(DAGNode):
    """DAG node that blocks on an EventListener; its value is the event
    payload."""

    def __init__(self, listener: EventListener, key: str):
        super().__init__()
        self._listener = listener
        self._key = key

    def _run(self, cache, dag_input):  # non-durable .execute() path
        raise RuntimeError("EventNode only executes inside workflow.run")


def wait_for_event(key_or_listener, **kwargs) -> EventNode:
    """`wait_for_event("approval")` waits for `trigger_event(wid,
    "approval", payload)`; or pass an EventListener instance."""
    if isinstance(key_or_listener, EventListener):
        key = getattr(key_or_listener, "key", None) or \
            f"listener-{type(key_or_listener).__name__}"
        return EventNode(key_or_listener, key)
    return EventNode(TriggerListener(key_or_listener, **kwargs),
                     key_or_listener)


def trigger_event(workflow_id: str, key: str, payload: Any = None):
    """Deliver an event payload to a (possibly waiting) workflow."""
    WorkflowStorage(workflow_id).post_event(key, payload)


# ---------------------------------------------------------------------------
# Management actor
# ---------------------------------------------------------------------------

_MANAGER_NAME = "__workflow_manager__"


@ray_tpu.remote
class _WorkflowManager:
    """Detached named actor making the workflow registry queryable from
    any driver (reference `workflow_access.py` WorkflowManagementActor).
    Storage stays the source of truth; the actor is the cluster-visible
    façade (and runs resume_all off-driver)."""

    def __init__(self, storage_root: Optional[str] = None):
        init(storage_root)

    def list_all(self):
        return list_all()

    def get_status(self, workflow_id: str):
        return get_status(workflow_id)

    def cancel(self, workflow_id: str):
        cancel(workflow_id)

    def run_async(self, dag, workflow_id=None, dag_input=None):
        return run(dag, workflow_id=workflow_id, dag_input=dag_input)

    def resume_all(self):
        return resume_all()


def get_management_actor(storage_root: Optional[str] = None):
    """Get or create the named workflow-management actor."""
    try:
        return ray_tpu.get_actor(_MANAGER_NAME)
    except Exception:  # noqa: BLE001
        try:
            return _WorkflowManager.options(
                name=_MANAGER_NAME).remote(storage_root or _root())
        except ValueError:  # lost the creation race
            return ray_tpu.get_actor(_MANAGER_NAME)
