"""ray_tpu.workflow: durable DAG execution.

Reference: `python/ray/workflow/` (SURVEY.md §2.4) — `workflow.run(dag)`
executes a `ray_tpu.dag` graph with per-step results checkpointed to
storage (`workflow_storage.py` equivalent), so a crashed workflow resumes
from completed steps; a management registry tracks status.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, InputNode

_storage_root: Optional[str] = None
_lock = threading.Lock()


def init(storage: Optional[str] = None):
    """Set the durable storage root (default ~/.ray_tpu_workflows)."""
    global _storage_root
    _storage_root = storage or os.path.expanduser("~/.ray_tpu_workflows")
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    if _storage_root is None:
        init()
    return _storage_root


class WorkflowStorage:
    """Filesystem-backed step-result store (reference:
    `workflow/workflow_storage.py`)."""

    def __init__(self, workflow_id: str):
        self.path = os.path.join(_root(), workflow_id)
        os.makedirs(os.path.join(self.path, "steps"), exist_ok=True)

    def _step_file(self, step_id: str) -> str:
        return os.path.join(self.path, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_file(step_id))

    def load_step(self, step_id: str):
        with open(self._step_file(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, value):
        tmp = self._step_file(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self._step_file(step_id))

    def set_status(self, status: str, error: str = ""):
        with open(os.path.join(self.path, "status"), "w") as f:
            f.write(f"{status}\n{error}")

    def get_status(self) -> str:
        try:
            with open(os.path.join(self.path, "status")) as f:
                return f.read().splitlines()[0]
        except OSError:
            return "NONE"


def _step_id_of(node: DAGNode) -> str:
    """Deterministic step id: structural position + function name."""
    name = getattr(getattr(node, "_fn", None), "__name__", None) or \
        type(node).__name__
    return f"{name}-{_structural_hash(node)[:12]}"


def _structural_hash(node: DAGNode, seen=None) -> str:
    seen = seen or {}
    if id(node) in seen:
        return seen[id(node)]
    parts = [type(node).__name__,
             getattr(getattr(node, "_fn", None), "__name__", "")]
    for a in node._bound_args:
        parts.append(_structural_hash(a, seen) if isinstance(a, DAGNode)
                     else repr(a))
    for k, v in sorted(node._bound_kwargs.items()):
        parts.append(k)
        parts.append(_structural_hash(v, seen) if isinstance(v, DAGNode)
                     else repr(v))
    h = hashlib.sha1("|".join(parts).encode()).hexdigest()
    seen[id(node)] = h
    return h


def _execute_durable(node: DAGNode, storage: WorkflowStorage, dag_input,
                     cache: Dict[str, Any]):
    if node._uuid in cache:
        return cache[node._uuid]
    if isinstance(node, InputNode):
        result = dag_input
    else:
        step_id = _step_id_of(node)
        if storage.has_step(step_id):
            result = storage.load_step(step_id)
        else:
            # Resolve children durably first, then run this step.
            args = tuple(
                _execute_durable(a, storage, dag_input, cache)
                if isinstance(a, DAGNode) else a
                for a in node._bound_args)
            kwargs = {
                k: _execute_durable(v, storage, dag_input, cache)
                if isinstance(v, DAGNode) else v
                for k, v in node._bound_kwargs.items()}
            fn = getattr(node, "_fn", None)
            if fn is None:
                raise TypeError(
                    f"workflow steps must be function nodes, got "
                    f"{type(node).__name__}")
            result = ray_tpu.get(fn.remote(*args, **kwargs))
            storage.save_step(step_id, result)
    cache[node._uuid] = result
    return result


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        dag_input: Any = None) -> Any:
    """Run (or resume) a workflow to completion, returning the output.
    Completed steps are skipped on resume."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    storage = WorkflowStorage(workflow_id)
    storage.set_status("RUNNING")
    try:
        result = _execute_durable(dag, storage, dag_input, {})
        storage.save_step("__output__", result)
        storage.set_status("SUCCESSFUL")
        return result
    except BaseException as e:  # noqa: BLE001
        storage.set_status("FAILED", str(e))
        raise


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              dag_input: Any = None):
    """Launch as a task; returns an ObjectRef of the output."""

    @ray_tpu.remote
    def _runner(payload):
        dag, wid, dinput = payload
        return run(dag, workflow_id=wid, dag_input=dinput)

    return _runner.remote((dag, workflow_id, dag_input))


def get_status(workflow_id: str) -> str:
    return WorkflowStorage(workflow_id).get_status()


def get_output(workflow_id: str):
    storage = WorkflowStorage(workflow_id)
    if not storage.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id} has no stored output")
    return storage.load_step("__output__")


def resume(workflow_id: str):
    """Re-run a failed workflow from its stored steps. The caller must
    re-supply the same DAG via `run` with the same workflow_id; this
    helper just returns the stored output when already successful."""
    storage = WorkflowStorage(workflow_id)
    if storage.get_status() == "SUCCESSFUL":
        return storage.load_step("__output__")
    raise ValueError(
        f"workflow {workflow_id} is {storage.get_status()}; re-issue "
        "run(dag, workflow_id=...) to resume execution")


def list_all() -> List[tuple]:
    root = _root()
    out = []
    for wid in sorted(os.listdir(root)):
        if os.path.isdir(os.path.join(root, wid)):
            out.append((wid, WorkflowStorage(wid).get_status()))
    return out
