"""External-environment serving: PolicyServer / PolicyClient.

Reference: `rllib/env/policy_server_input.py` + `policy_client.py` — an
external simulator (a game server, a robot, another process) drives
episodes over the wire: it asks the server for actions, logs rewards,
and ends episodes; the server turns that traffic into SampleBatches an
algorithm trains from, and pushes fresh weights to inference.

The wire is the framework's own framed RPC (`_private/rpc.py`) — same
channel the control plane uses, TLS-capable. Inference runs server-side
(the client never needs model code), host-CPU by default like rollout
workers.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
    TERMINATEDS,
    VALUES,
)


class _Episode:
    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logps: List[float] = []
        self.values: List[float] = []
        self.rewards: List[float] = []


class PolicyServer:
    """Serves actions to external simulators and accumulates their
    experience (reference PolicyServerInput)."""

    def __init__(self, apply_fn, params, *, host: str = "127.0.0.1",
                 port: int = 0, batch_size: int = 256,
                 deterministic: bool = False, seed: int = 0):
        import jax

        self._apply = jax.jit(apply_fn)
        self._params = params
        self._lock = threading.Lock()
        self._episodes: Dict[str, _Episode] = {}
        self._rng = np.random.RandomState(seed)
        self._deterministic = deterministic
        self._batch_size = batch_size
        self._rows: Dict[str, list] = {
            k: [] for k in (OBS, ACTIONS, REWARDS, DONES, TERMINATEDS,
                            NEXT_OBS, LOGPS, VALUES)}
        self._batches: "queue.Queue[SampleBatch]" = queue.Queue()
        self.episode_returns: List[float] = []
        self._server = RpcServer({
            "start_episode": self._start_episode,
            "get_action": self._get_action,
            "log_returns": self._log_returns,
            "end_episode": self._end_episode,
        }, host=host, port=port)
        self.address = self._server.address

    # -- weights ---------------------------------------------------------

    def set_weights(self, params) -> None:
        with self._lock:
            self._params = params

    # -- RPC handlers ----------------------------------------------------

    def _start_episode(self, episode_id: Optional[str] = None) -> str:
        eid = episode_id or uuid.uuid4().hex[:12]
        with self._lock:
            self._episodes[eid] = _Episode()
        return eid

    def _compute(self, obs: np.ndarray):
        import jax

        logits, value = self._apply(self._params, obs[None])
        logits = np.asarray(jax.device_get(logits), np.float32)[0]
        value = float(np.asarray(jax.device_get(value))[0])
        logp_all = logits - _logsumexp(logits)
        if self._deterministic:
            action = int(logits.argmax())
        else:
            z = self._rng.gumbel(size=logits.shape)
            action = int((logits + z).argmax())
        return action, float(logp_all[action]), value

    def _get_action(self, episode_id: str, obs) -> int:
        obs = np.asarray(obs, np.float32)
        with self._lock:
            ep = self._episodes[episode_id]
            action, logp, value = self._compute(obs)
            ep.obs.append(obs)
            ep.actions.append(action)
            ep.logps.append(logp)
            ep.values.append(value)
            return action

    def _log_returns(self, episode_id: str, reward: float) -> bool:
        with self._lock:
            self._episodes[episode_id].rewards.append(float(reward))
        return True

    def _end_episode(self, episode_id: str, last_obs) -> bool:
        last = np.asarray(last_obs, np.float32)
        with self._lock:
            ep = self._episodes.pop(episode_id)
            n = len(ep.actions)
            if n == 0:
                return True
            rewards = (ep.rewards + [0.0] * n)[:n]
            self.episode_returns.append(float(sum(rewards)))
            next_obs = ep.obs[1:] + [last]
            for i in range(n):
                terminated = i == n - 1
                self._rows[OBS].append(ep.obs[i])
                self._rows[ACTIONS].append(ep.actions[i])
                self._rows[REWARDS].append(rewards[i])
                self._rows[DONES].append(terminated)
                self._rows[TERMINATEDS].append(terminated)
                self._rows[NEXT_OBS].append(next_obs[i])
                self._rows[LOGPS].append(ep.logps[i])
                self._rows[VALUES].append(ep.values[i])
            if len(self._rows[OBS]) >= self._batch_size:
                self._batches.put(SampleBatch({
                    k: np.asarray(v) for k, v in self._rows.items()}))
                self._rows = {k: [] for k in self._rows}
        return True

    # -- training-side API ----------------------------------------------

    def get_samples(self, timeout: Optional[float] = None
                    ) -> Optional[SampleBatch]:
        """Next accumulated batch (None on timeout) — the algorithm's
        sample source, the PolicyServerInput role."""
        try:
            return self._batches.get(
                timeout=timeout) if timeout is not None \
                else self._batches.get_nowait()
        except queue.Empty:
            return None

    def shutdown(self):
        self._server.shutdown()


class PolicyClient:
    """The external simulator's handle (reference PolicyClient)."""

    def __init__(self, address):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host, int(port))
        self._rpc = RpcClient.dedicated(tuple(address))

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        return self._rpc.call("start_episode", episode_id=episode_id)

    def get_action(self, episode_id: str, observation) -> int:
        return self._rpc.call(
            "get_action", episode_id=episode_id,
            obs=np.asarray(observation, np.float32))

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._rpc.call("log_returns", episode_id=episode_id,
                       reward=float(reward))

    def end_episode(self, episode_id: str, observation) -> None:
        self._rpc.call("end_episode", episode_id=episode_id,
                       last_obs=np.asarray(observation, np.float32))

    def close(self):
        self._rpc.close()


def _logsumexp(x):
    m = x.max()
    return m + np.log(np.exp(x - m).sum())
