"""Offline IO: write/read SampleBatch experience to/from JSONL files.

Reference: `rllib/offline/` — `JsonWriter` (rollouts → newline-delimited
JSON with base64 arrays), `JsonReader` (files → SampleBatch stream),
`InputReader` ABC so algorithms can consume either live rollouts or
recorded data. Used by the offline algorithms (BC/MARWIL) and for
dataset export.
"""

from __future__ import annotations

import base64
import glob as globlib
import io
import json
import os
from typing import Iterator, List, Optional, Sequence, Union

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


def _encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__npy__": base64.b64encode(buf.getvalue()).decode("ascii")}


def _decode_value(v):
    if isinstance(v, dict) and "__npy__" in v:
        return np.load(io.BytesIO(base64.b64decode(v["__npy__"])),
                       allow_pickle=False)
    return np.asarray(v)


class InputReader:
    """Source of training batches (reference `rllib/offline/io.py`)."""

    def next(self) -> SampleBatch:
        raise NotImplementedError


class JsonWriter:
    """Append SampleBatches to JSONL files, rolling at max_file_size."""

    def __init__(self, path: str, max_file_size: int = 64 << 20):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._index = 0
        self._file = None

    def _roll(self):
        if self._file is not None:
            self._file.close()
        name = os.path.join(self.path, f"output-{self._index:05d}.json")
        self._index += 1
        self._file = open(name, "w")

    def write(self, batch: SampleBatch):
        if self._file is None or self._file.tell() > self.max_file_size:
            self._roll()
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader(InputReader):
    """Read SampleBatches back from JSONL files (cycling forever)."""

    def __init__(self, inputs: Union[str, Sequence[str]]):
        if isinstance(inputs, str):
            if os.path.isdir(inputs):
                inputs = sorted(
                    globlib.glob(os.path.join(inputs, "*.json")))
            else:
                inputs = sorted(globlib.glob(inputs)) or [inputs]
        self.files: List[str] = list(inputs)
        if not self.files:
            raise ValueError("JsonReader: no input files")
        self._iter: Optional[Iterator[SampleBatch]] = None

    def _read_all(self) -> Iterator[SampleBatch]:
        for path in self.files:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    yield SampleBatch({k: _decode_value(v)
                                       for k, v in row.items()})

    def next(self) -> SampleBatch:
        if self._iter is None:
            self._iter = self._read_all()
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = self._read_all()
            return next(self._iter)

    def read_all(self) -> SampleBatch:
        """Materialize every batch concatenated (for small datasets)."""
        return SampleBatch.concat(list(self._read_all()))
