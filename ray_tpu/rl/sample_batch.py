"""SampleBatch: the trajectory data container.

Reference: `rllib/policy/sample_batch.py` — a dict of parallel arrays with
concat/split/shuffle, plus the standard column names.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"            # terminated OR truncated (episode boundary)
TERMINATEDS = "terminateds"  # env-terminal only (bootstrap mask)
NEXT_OBS = "next_obs"
LOGPS = "action_logp"
VALUES = "values"
STATE_IN = "state_in"      # recurrent hidden state entering each step
ADVANTAGES = "advantages"
TARGETS = "value_targets"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in keys
        })

    def shuffle(self, rng: np.random.RandomState) -> "SampleBatch":
        idx = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[idx] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch({k: np.asarray(v)[start:start + size]
                               for k, v in self.items()})

    def split(self, n: int) -> List["SampleBatch"]:
        out = []
        for idx in np.array_split(np.arange(self.count), n):
            out.append(SampleBatch({k: np.asarray(v)[idx]
                                    for k, v in self.items()}))
        return out
