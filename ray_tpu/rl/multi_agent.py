"""Multi-agent rollout support.

Reference: `rllib/env/multi_agent_env.py` + the multi-agent paths of
`rollout_worker.py`/`sampler.py` — an env whose reset/step speak dicts
keyed by agent id, a policy-mapping function assigning each agent to a
policy, and sampling that produces one SampleBatch PER POLICY (agents
mapped to the same policy share a batch, the "parameter sharing" setup).

Scope: discrete-action categorical policies, one env per worker. The
returned batches are row-flat ([steps, ...]) and carry the standard
columns, so the single-agent learner updates (PPO/A2C losses) apply
unchanged per policy.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import MultiAgentEnv
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
    TERMINATEDS,
    VALUES,
)


@ray_tpu.remote
class MultiAgentRolloutWorker:
    """Samples fragments from one MultiAgentEnv.

    policy_applies: {policy_id: apply_fn(weights, obs) -> (logits, values)}
    policy_mapping_fn: agent_id -> policy_id
    """

    def __init__(self, env_creator: Callable[..., MultiAgentEnv],
                 policy_applies: Dict[str, Callable], *,
                 policy_mapping_fn: Callable[[str], str],
                 env_config: Optional[dict] = None,
                 rollout_fragment_length: int = 100, seed: int = 0):
        import jax

        self.env = env_creator(env_config or {})
        self.applies = {pid: jax.jit(fn)
                        for pid, fn in policy_applies.items()}
        self.mapping = policy_mapping_fn
        self.fragment = rollout_fragment_length
        self._rng = np.random.RandomState(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_len = 0
        self._completed: list = []

    def sample(self, weights_per_policy: Dict[str, Any]) -> Dict[
            str, SampleBatch]:
        rows: Dict[str, Dict[str, list]] = {}

        def _rows(pid):
            return rows.setdefault(pid, {
                OBS: [], ACTIONS: [], REWARDS: [], DONES: [],
                TERMINATEDS: [], NEXT_OBS: [], LOGPS: [], VALUES: []})

        for _ in range(self.fragment):
            # Group live agents by policy and batch their inference.
            actions: Dict[str, Any] = {}
            step_info: Dict[str, tuple] = {}
            by_policy: Dict[str, list] = {}
            for aid in self.obs:
                by_policy.setdefault(self.mapping(aid), []).append(aid)
            for pid, aids in by_policy.items():
                obs_arr = np.stack([np.asarray(self.obs[a], np.float32)
                                    for a in aids])
                logits, values = self.applies[pid](
                    weights_per_policy[pid], obs_arr)
                logits = np.asarray(logits, np.float32)
                z = self._rng.gumbel(size=logits.shape)
                acts = (logits + z).argmax(-1)
                logp = logits - _logsumexp(logits)
                act_logp = np.take_along_axis(
                    logp, acts[:, None], axis=1)[:, 0]
                for i, aid in enumerate(aids):
                    actions[aid] = int(acts[i])
                    step_info[aid] = (pid, obs_arr[i], acts[i],
                                      act_logp[i],
                                      float(np.asarray(values)[i]))
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            done_all = bool(terms.get("__all__", False)
                            or truncs.get("__all__", False))
            for aid, (pid, ob, act, lp, val) in step_info.items():
                r = _rows(pid)
                term = bool(terms.get(aid, False))
                trunc = bool(truncs.get(aid, False))
                r[OBS].append(ob)
                r[ACTIONS].append(act)
                r[REWARDS].append(float(rewards.get(aid, 0.0)))
                r[DONES].append(term or trunc or done_all)
                r[TERMINATEDS].append(term)
                r[NEXT_OBS].append(np.asarray(
                    next_obs.get(aid, ob), np.float32))
                r[LOGPS].append(lp)
                r[VALUES].append(val)
                self._episode_reward += float(rewards.get(aid, 0.0))
            self._episode_len += 1
            if done_all:
                self._completed.append(
                    (self._episode_reward, self._episode_len))
                self._episode_reward, self._episode_len = 0.0, 0
                self.obs, _ = self.env.reset()
            else:
                self.obs = next_obs
        return {pid: SampleBatch({k: np.asarray(v)
                                  for k, v in r.items()})
                for pid, r in rows.items()}

    def episode_stats(self, clear: bool = True):
        stats = list(self._completed)
        if clear:
            self._completed = []
        return stats


def _logsumexp(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=axis, keepdims=True))
