"""Algorithm base + fluent AlgorithmConfig.

Reference: `rllib/algorithms/algorithm.py:149` (Algorithm extends
Trainable; `step` = one Tune iteration) and `algorithm_config.py` (fluent
config). Algorithms here follow the same shape: `config.build()` →
`algo.train()` loops, and `Algorithm` subclasses `tune.Trainable` so Tune
schedules RL experiments unchanged.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Type

import numpy as np

import ray_tpu
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env_spec: Any = None
        self.env_config: dict = {}
        self.num_rollout_workers: int = 2
        self.num_envs_per_worker: int = 1
        self.rollout_fragment_length: int = 200
        # Where worker-side policy inference runs ("cpu" keeps the
        # accelerator exclusively for the learner).
        self.inference_device: str = "cpu"
        # Connector pipelines (ray_tpu.rl.connectors); pickled out to
        # each worker, so every worker gets its own copy.
        self.obs_connectors: Any = None
        self.action_connectors: Any = None
        self.train_batch_size: int = 2000
        self.lr: float = 5e-4
        self.gamma: float = 0.99
        self.seed: int = 0
        self.extra: Dict[str, Any] = {}

    # fluent API (reference naming)
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env_spec = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def rollouts(self, *, num_rollout_workers=None,
                 num_envs_per_worker=None,
                 rollout_fragment_length=None,
                 obs_connectors=None,
                 action_connectors=None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if obs_connectors is not None:
            self.obs_connectors = obs_connectors
        if action_connectors is not None:
            self.action_connectors = action_connectors
        return self

    env_runners = rollouts  # new-stack alias

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def resources(self, **kwargs) -> "AlgorithmConfig":
        self.extra.update(kwargs)
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        assert self.algo_class is not None, "no algorithm class bound"
        return self.algo_class(self)

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class",)}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "AlgorithmConfig":
        """Round-trip counterpart of :meth:`to_dict` (raylint R5:
        serialization contracts come in pairs). ``algo_class`` is not
        serialized; re-bind with ``.build()`` via a bound subclass."""
        cfg = cls.__new__(cls)
        cfg.__dict__.update(copy.deepcopy(d))
        cfg.algo_class = None
        return cfg


class WorkerSet:
    """Reference: `rllib/evaluation/worker_set.py` — the rollout fleet."""

    def __init__(self, config: AlgorithmConfig, policy_apply: Callable,
                 policy_kind: str = "actor_critic", state_size: int = 0,
                 append_prev_action: bool = False):
        from ray_tpu.rl.rollout_worker import RolloutWorker

        self.workers = [
            RolloutWorker.remote(
                config.env_spec, policy_apply,
                num_envs=config.num_envs_per_worker,
                env_config=config.env_config,
                rollout_fragment_length=config.rollout_fragment_length,
                seed=config.seed + 1000 * (i + 1),
                policy_kind=policy_kind,
                obs_connectors=config.obs_connectors,
                action_connectors=config.action_connectors,
                inference_device=config.inference_device,
                state_size=state_size,
                append_prev_action=append_prev_action)
            for i in range(max(1, config.num_rollout_workers))
        ]

    def sample(self, weights) -> List:
        ref_w = ray_tpu.put(weights)
        return ray_tpu.get([w.sample.remote(ref_w) for w in self.workers])

    def connector_state(self):
        """State of worker 0's connector pipelines (the canonical copy
        for checkpointing)."""
        return ray_tpu.get(self.workers[0].connector_state.remote())

    def set_connector_state(self, state):
        ray_tpu.get([w.set_connector_state.remote(state)
                     for w in self.workers])

    def episode_stats(self) -> List:
        out = []
        for stats in ray_tpu.get([w.episode_stats.remote()
                                  for w in self.workers]):
            out.extend(stats)
        return out

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


class Algorithm(Trainable):
    """One RL algorithm instance; `train()` = one iteration."""

    config_cls = AlgorithmConfig

    def __init__(self, config=None):
        if isinstance(config, AlgorithmConfig):
            self.algo_config = config
            super().__init__(config.to_dict())
        else:
            self.algo_config = self.config_cls()
            if config:
                self.algo_config.training(**{
                    k: v for k, v in dict(config).items()})
                if "env" in (config or {}):
                    self.algo_config.environment(config["env"])
            super().__init__(config or {})
        self._iter_stats: Dict[str, Any] = {}
        self._episode_window: List[float] = []

    # Trainable hooks --------------------------------------------------

    def setup(self, config):
        self.build_components()

    def build_components(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        metrics = self.training_step()
        stats = self.workers.episode_stats() if hasattr(self, "workers") \
            else []
        for r, _ in stats:
            self._episode_window.append(r)
        self._episode_window = self._episode_window[-100:]
        if self._episode_window:
            metrics["episode_reward_mean"] = float(
                np.mean(self._episode_window))
            metrics["episodes_this_iter"] = len(stats)
        return metrics

    def compute_single_action(self, obs, explore: bool = False):
        """Greedy (or sampled, explore=True) action from the current
        policy — reference `Algorithm.compute_single_action`. Covers the
        built-in policy families by parameter shape: actor-critic
        (logits), Q-network, and tanh-Gaussian continuous."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        weights = self.get_weights()
        params = weights.get("params", weights) \
            if isinstance(weights, dict) else weights
        obs_np = np.asarray(obs)
        if obs_np.dtype == np.float64:
            obs_np = obs_np.astype(np.float32)
        obs_b = jnp.asarray(obs_np)[None]  # integer frames stay integer:
        # the conv torso rescales on device (train/eval parity)
        if isinstance(params, dict) and ("conv" in params or
                                         "pi" in params):
            apply = models.cnn_actor_critic_apply if "conv" in params \
                else models.actor_critic_apply
            logits, _ = apply(params, obs_b)
            if explore:
                key = jax.random.PRNGKey(np.random.randint(2 ** 31))
                return int(jax.random.categorical(key, logits)[0])
            return int(jnp.argmax(logits, -1)[0])
        if isinstance(params, dict) and "q" in params:
            return int(jnp.argmax(models.q_net_apply(params, obs_b),
                                  -1)[0])
        if isinstance(params, dict) and "actor" in params:
            mean, _ = models.gaussian_policy_apply(params["actor"],
                                                   obs_b)
            return np.asarray(jnp.tanh(mean)[0])
        raise NotImplementedError(
            f"{type(self).__name__} has no evaluable policy shape")

    def evaluate(self, num_episodes: int = 5,
                 max_steps_per_episode: int = 1000) -> Dict[str, Any]:
        """Run the current policy WITHOUT exploration for N episodes
        (reference `Algorithm.evaluate` / evaluation workers). Returns
        episode_reward_mean/min/max and mean length."""
        from ray_tpu.rl.env import Box, make_env

        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        continuous = isinstance(env.action_space, Box)
        rewards, lengths = [], []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=cfg.seed + 10_000 + ep)
            total, steps = 0.0, 0
            for _ in range(max_steps_per_episode):
                a = self.compute_single_action(obs)
                if continuous:
                    low, high = env.action_space.low, \
                        env.action_space.high
                    a = low + (np.asarray(a) + 1.0) * 0.5 * (high - low)
                obs, r, term, trunc, _ = env.step(a)
                total += r
                steps += 1
                if term or trunc:
                    break
            rewards.append(total)
            lengths.append(steps)
        env.close()
        return {
            "evaluation": {
                "episode_reward_mean": float(np.mean(rewards)),
                "episode_reward_min": float(np.min(rewards)),
                "episode_reward_max": float(np.max(rewards)),
                "episode_len_mean": float(np.mean(lengths)),
                "episodes": num_episodes,
            }
        }

    def cleanup(self):
        if hasattr(self, "workers"):
            self.workers.stop()

    def save_checkpoint(self):
        import jax

        ckpt = {"weights": jax.device_get(self.get_weights())}
        if hasattr(self, "workers"):
            ckpt["connectors"] = self.workers.connector_state()
        return ckpt

    def load_checkpoint(self, data):
        self.set_weights(data["weights"])
        if data.get("connectors") and hasattr(self, "workers"):
            self.workers.set_connector_state(data["connectors"])

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights):
        raise NotImplementedError
