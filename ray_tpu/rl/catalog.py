"""Model catalog: observation/action spaces → policy networks.

Reference: `rllib/models/catalog.py` — algorithms ask the catalog for a
model matching the env's spaces instead of hard-coding torsos. The JAX
catalog maps:

- Box/flat observations → MLP torso
- [H, W, C] image observations → nature-CNN torso
- Discrete actions → categorical actor-critic or Q-head
- Box actions → tanh-squashed diagonal Gaussian

returning ``(init_fn(rng) -> params, apply_fn(params, obs))`` pairs the
rollout workers and learners share (the apply is what WorkerSet jits).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.env import Box, Discrete


@dataclass
class ModelConfig:
    """Reference `MODEL_DEFAULTS` subset."""

    hidden: Tuple[int, ...] = (64, 64)
    cnn_hidden: int = 256


@dataclass
class ModelSpec:
    init: Callable[[Any], Any]          # rng -> params
    apply: Callable[[Any, Any], Any]    # (params, obs) -> outputs
    kind: str = "actor_critic"          # WorkerSet policy_kind


def _is_image(space) -> bool:
    return hasattr(space, "shape") and len(space.shape) == 3


def get_actor_critic_model(obs_space, action_space,
                           config: Optional[ModelConfig] = None
                           ) -> ModelSpec:
    """Policy+value model for PG-family algorithms (PPO/IMPALA/APPO...)."""
    cfg = config or ModelConfig()
    if isinstance(action_space, Discrete):
        n = action_space.n
        if _is_image(obs_space):
            shape = obs_space.shape
            return ModelSpec(
                init=lambda rng: models.cnn_actor_critic_init(
                    rng, shape, n, hidden=cfg.cnn_hidden),
                apply=models.cnn_actor_critic_apply,
                kind="actor_critic")
        obs_dim = int(np.prod(obs_space.shape))
        return ModelSpec(
            init=lambda rng: models.actor_critic_init(
                rng, obs_dim, n, cfg.hidden),
            apply=models.actor_critic_apply,
            kind="actor_critic")
    if isinstance(action_space, Box):
        obs_dim = int(np.prod(obs_space.shape))
        act_dim = int(np.prod(action_space.shape))
        return ModelSpec(
            init=lambda rng: models.gaussian_policy_init(
                rng, obs_dim, act_dim, cfg.hidden),
            apply=models.gaussian_policy_apply,
            kind="gaussian")
    raise ValueError(f"unsupported action space: {action_space!r}")


def get_q_model(obs_space, action_space,
                config: Optional[ModelConfig] = None) -> ModelSpec:
    """Q-network for value-based algorithms (DQN family)."""
    cfg = config or ModelConfig()
    assert isinstance(action_space, Discrete), \
        "Q models need discrete actions"
    obs_dim = int(np.prod(obs_space.shape))
    return ModelSpec(
        init=lambda rng: models.q_net_init(rng, obs_dim,
                                           action_space.n, cfg.hidden),
        apply=models.q_net_apply,
        kind="q")
