"""Replay helpers + buffers (reference `rllib/utils/replay_buffers/`)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform ring buffer over flat transitions."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[dict] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return self._size

    def add(self, batch: SampleBatch):
        n = batch.count
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, n: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=n)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (sum-tree-free O(n) variant — fine for
    host-side buffers at these sizes)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch):
        n = batch.count
        idx = (self._idx + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_prio

    def sample(self, n: int) -> SampleBatch:
        prios = self._priorities[: self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=n, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._storage.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities):
        priorities = np.abs(priorities) + 1e-6
        self._priorities[idx] = priorities
        self._max_prio = max(self._max_prio, priorities.max())


class ReservoirReplayBuffer(ReplayBuffer):
    """Reservoir sampling buffer (reference: league-based algos)."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        super().__init__(capacity, seed)
        self._seen = 0

    def add(self, batch: SampleBatch):
        n = batch.count
        if self._storage is None or self._size < self.capacity:
            super().add(batch)
            self._seen += n
            return
        # One slot draw per incoming transition, applied to every storage
        # key — per-key draws would scatter one transition's fields across
        # unrelated rows.
        arrays = {k: np.asarray(batch[k]) for k in self._storage}
        for i in range(n):
            j = self._rng.randint(0, self._seen + i + 1)
            if j < self.capacity:
                for k, v in arrays.items():
                    self._storage[k][j] = v[i]
        self._seen += n


def flatten_fragments(batches) -> SampleBatch:
    """[N, T, ...] rollout fragments (one per worker) → one flat
    [sum(N*T), ...] SampleBatch. Shared by the off-policy algorithms'
    replay ingestion (DQN/SAC/TD3) — keep the reshape in ONE place."""
    from ray_tpu.rl.sample_batch import REWARDS

    flat = []
    for b in batches:
        n, t = np.asarray(b[REWARDS]).shape
        flat.append(SampleBatch({
            k: np.asarray(v).reshape(n * t, *np.asarray(v).shape[2:])
            for k, v in b.items()
        }))
    return SampleBatch.concat(flat)


def sample_stacked(buffer: "ReplayBuffer", n_steps: int,
                   batch_size: int, keys) -> dict:
    """Draw n_steps minibatches and stack them [n_steps, batch, ...] for
    a scan-fused SGD phase (one jit dispatch per training iteration)."""
    import jax.numpy as jnp

    mbs = [buffer.sample(batch_size) for _ in range(n_steps)]
    return {
        k: jnp.asarray(np.stack([np.asarray(mb[k]) for mb in mbs]))
        for k in keys
    }
