"""Replay helpers + buffers (reference `rllib/utils/replay_buffers/`)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform ring buffer over flat transitions."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[dict] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return self._size

    def add(self, batch: SampleBatch):
        n = batch.count
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, n: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=n)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})


def _proportional_sample(priorities, size, n, alpha, beta, rng):
    """Shared PER sampling core: proportional draw over
    priorities[:size]**alpha + max-normalized IS weights (reference
    `rllib/utils/replay_buffers/prioritized_replay_buffer.py`)."""
    prios = priorities[:size] ** alpha
    probs = prios / prios.sum()
    idx = rng.choice(size, size=n, p=probs)
    weights = (size * probs[idx]) ** (-beta)
    weights /= weights.max()
    return idx, weights.astype(np.float32)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (sum-tree-free O(n) variant — fine for
    host-side buffers at these sizes)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch):
        n = batch.count
        idx = (self._idx + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_prio

    def sample(self, n: int) -> SampleBatch:
        idx, weights = _proportional_sample(
            self._priorities, self._size, n, self.alpha, self.beta,
            self._rng)
        out = SampleBatch({k: v[idx] for k, v in self._storage.items()})
        out["weights"] = weights
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities):
        priorities = np.abs(priorities) + 1e-6
        self._priorities[idx] = priorities
        self._max_prio = max(self._max_prio, priorities.max())


class ReservoirReplayBuffer(ReplayBuffer):
    """Reservoir sampling buffer (reference: league-based algos)."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        super().__init__(capacity, seed)
        self._seen = 0

    def add(self, batch: SampleBatch):
        n = batch.count
        if self._storage is None or self._size < self.capacity:
            super().add(batch)
            self._seen += n
            return
        # One slot draw per incoming transition, applied to every storage
        # key — per-key draws would scatter one transition's fields across
        # unrelated rows.
        arrays = {k: np.asarray(batch[k]) for k in self._storage}
        for i in range(n):
            j = self._rng.randint(0, self._seen + i + 1)
            if j < self.capacity:
                for k, v in arrays.items():
                    self._storage[k][j] = v[i]
        self._seen += n


class SequenceReplayBuffer:
    """Replay of fixed-length [L, ...] subsequences with stored initial
    recurrent state — the R2D2 "stored state" strategy (reference:
    `rllib/algorithms/r2d2/` + `rllib/utils/replay_buffers/
    multi_agent_replay_buffer.py` sequence support).

    `add` takes [N, T, ...] rollout fragments carrying a "state_in"
    column ([N, T, H]); each env row is chopped into windows of
    `burn_in + seq_len` steps (stride `seq_len`, trailing remainder
    dropped) and the hidden state at the window start is stored as the
    sequence's initial state. Sampling is proportional-prioritized with
    the R2D2 mix p = eta*max|td| + (1-eta)*mean|td| supplied by the
    learner via `update_priorities`."""

    def __init__(self, capacity: int = 4096, seq_len: int = 32,
                 burn_in: int = 8, seed: int = 0, alpha: float = 0.6,
                 beta: float = 0.4):
        self.capacity = capacity
        self.L = burn_in + seq_len
        self.burn_in = burn_in
        self.seq_len = seq_len
        self.alpha = alpha
        self.beta = beta
        self._storage: Optional[dict] = None
        self._state0: Optional[np.ndarray] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.RandomState(seed)
        self._priorities = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def __len__(self):
        return self._size

    def add(self, batch: SampleBatch):
        from ray_tpu.rl.sample_batch import STATE_IN

        arrays = {k: np.asarray(v) for k, v in batch.items()}
        state_in = arrays.pop(STATE_IN)
        n, t = state_in.shape[:2]
        if t < self.L:
            raise ValueError(
                f"rollout fragments are {t} steps but sequences need "
                f"burn_in + seq_len = {self.L}; raise "
                "rollout_fragment_length or shrink the sequence window")
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity, self.L, *v.shape[2:]),
                            v.dtype)
                for k, v in arrays.items()
            }
            self._state0 = np.zeros((self.capacity, state_in.shape[-1]),
                                    np.float32)
        for row in range(n):
            for start in range(0, t - self.L + 1, self.seq_len):
                i = self._idx
                for k, v in arrays.items():
                    self._storage[k][i] = v[row, start:start + self.L]
                self._state0[i] = state_in[row, start]
                self._priorities[i] = self._max_prio
                self._idx = (self._idx + 1) % self.capacity
                self._size = min(self._size + 1, self.capacity)

    def sample(self, n: int) -> dict:
        idx, weights = _proportional_sample(
            self._priorities, self._size, n, self.alpha, self.beta,
            self._rng)
        out = {k: v[idx] for k, v in self._storage.items()}
        out["state0"] = self._state0[idx]
        out["weights"] = weights
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities):
        priorities = np.abs(np.asarray(priorities)) + 1e-6
        self._priorities[idx] = priorities
        self._max_prio = max(self._max_prio, float(priorities.max()))


def flatten_fragments(batches) -> SampleBatch:
    """[N, T, ...] rollout fragments (one per worker) → one flat
    [sum(N*T), ...] SampleBatch. Shared by the off-policy algorithms'
    replay ingestion (DQN/SAC/TD3) — keep the reshape in ONE place."""
    from ray_tpu.rl.sample_batch import REWARDS

    flat = []
    for b in batches:
        n, t = np.asarray(b[REWARDS]).shape
        flat.append(SampleBatch({
            k: np.asarray(v).reshape(n * t, *np.asarray(v).shape[2:])
            for k, v in b.items()
        }))
    return SampleBatch.concat(flat)


def sample_stacked(buffer: "ReplayBuffer", n_steps: int,
                   batch_size: int, keys) -> dict:
    """Draw n_steps minibatches and stack them [n_steps, batch, ...] for
    a scan-fused SGD phase (one jit dispatch per training iteration)."""
    import jax.numpy as jnp

    mbs = [buffer.sample(batch_size) for _ in range(n_steps)]
    return {
        k: jnp.asarray(np.stack([np.asarray(mb[k]) for mb in mbs]))
        for k in keys
    }
