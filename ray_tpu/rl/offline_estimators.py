"""Off-policy estimators (OPE): value a TARGET policy from data a
BEHAVIOR policy collected.

Reference: `rllib/offline/estimators/` — ImportanceSampling (IS),
WeightedImportanceSampling (WIS), DirectMethod. The estimators consume
SampleBatches carrying the behavior policy's `action_logp` column
(exactly what the rollout workers record) and a target policy given as
``apply_fn(params, obs) -> (logits, values)``.

Per-decision importance sampling with discounting:

    V_IS  = E_episodes[ sum_t gamma^t * w_{0:t} * r_t ]
    V_WIS = same, with w_{0:t} normalized per step across episodes
            (self-normalized: bounded variance, slight bias)

DirectMethod fits nothing here — it evaluates the TARGET policy's own
value head on the initial states (the Q/V-model role), useful as a
cheap sanity bound.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    SampleBatch,
)


def _episodes(batch: SampleBatch) -> List[Dict[str, np.ndarray]]:
    """Split a row-flat batch into episodes at done boundaries (a
    trailing partial episode is kept — standard for fragment data)."""
    dones = np.asarray(batch[DONES]).astype(bool)
    out = []
    start = 0
    for i, d in enumerate(dones):
        if d:
            out.append({k: np.asarray(v)[start:i + 1]
                        for k, v in batch.items()})
            start = i + 1
    if start < len(dones):
        out.append({k: np.asarray(v)[start:]
                    for k, v in batch.items()})
    return out


def _target_logps(apply_fn: Callable, params: Any,
                  obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    logits = apply_fn(params, jnp.asarray(obs, jnp.float32))
    if isinstance(logits, tuple):
        logits = logits[0]
    logp = jax.nn.log_softmax(logits)
    return np.asarray(jnp.take_along_axis(
        logp, jnp.asarray(actions)[:, None], axis=1)[:, 0])


class OffPolicyEstimator:
    """Base: estimate(batch) -> {v_behavior, v_target, ...}."""

    def __init__(self, apply_fn: Callable, params: Any,
                 gamma: float = 0.99, ratio_clip: float = 20.0):
        self.apply_fn = apply_fn
        self.params = params
        self.gamma = gamma
        self.ratio_clip = ratio_clip

    def _per_episode(self, batch: SampleBatch):
        if batch.count == 0:
            raise ValueError("cannot estimate from an empty batch")
        # ONE batched target forward over the row-flat batch, sliced
        # per episode after — a dispatch per episode would make JAX
        # overhead dominate on short-episode data.
        all_tgt = _target_logps(self.apply_fn, self.params,
                                np.asarray(batch[OBS]),
                                np.asarray(batch[ACTIONS]))
        rows = []
        start = 0
        for ep in _episodes(batch):
            n = len(ep[REWARDS])
            rew = ep[REWARDS].astype(np.float64)
            disc = self.gamma ** np.arange(n)
            tgt_logp = all_tgt[start:start + n]
            beh_logp = ep[LOGPS].astype(np.float64)
            # cumulative importance weights w_{0:t}, clipped for
            # variance control (reference caps likewise)
            w = np.exp(np.cumsum(tgt_logp - beh_logp))
            w = np.minimum(w, self.ratio_clip)
            rows.append({"rew": rew, "disc": disc, "w": w})
            start += n
        return rows

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision IS (reference
    `rllib/offline/estimators/importance_sampling.py`)."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        rows = self._per_episode(batch)
        v_beh = float(np.mean([(r["disc"] * r["rew"]).sum()
                               for r in rows]))
        v_tgt = float(np.mean([(r["disc"] * r["w"] * r["rew"]).sum()
                               for r in rows]))
        return {"v_behavior": v_beh, "v_target": v_tgt,
                "v_gain": v_tgt / v_beh if v_beh else float("nan"),
                "episodes": len(rows)}


class WeightedImportanceSampling(OffPolicyEstimator):
    """Self-normalized per-decision IS (reference
    `weighted_importance_sampling.py`): weights normalized across
    episodes at each timestep — bounded variance, slight bias."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        rows = self._per_episode(batch)
        max_t = max(len(r["rew"]) for r in rows)
        v_tgt = 0.0
        for t in range(max_t):
            live = [r for r in rows if len(r["rew"]) > t]
            wsum = sum(r["w"][t] for r in live)
            if wsum <= 0:
                continue
            v_tgt += sum(r["disc"][t] * r["w"][t] * r["rew"][t]
                         for r in live) / wsum * len(live) / len(rows)
        v_beh = float(np.mean([(r["disc"] * r["rew"]).sum()
                               for r in rows]))
        return {"v_behavior": v_beh, "v_target": float(v_tgt),
                "v_gain": v_tgt / v_beh if v_beh else float("nan"),
                "episodes": len(rows)}


class DirectMethod(OffPolicyEstimator):
    """Evaluate the target policy's OWN value head on episode starts
    (reference `direct_method.py`, with the policy's critic standing in
    for a separately fitted Q-model)."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp

        if batch.count == 0:
            raise ValueError("cannot estimate from an empty batch")
        eps = _episodes(batch)
        starts = np.stack([ep[OBS][0] for ep in eps])
        out = self.apply_fn(self.params, jnp.asarray(starts,
                                                     jnp.float32))
        if not (isinstance(out, tuple) and len(out) == 2):
            raise ValueError("DirectMethod needs an apply_fn returning "
                             "(logits, values)")
        values = np.asarray(out[1], np.float64)
        v_beh = float(np.mean([
            (self.gamma ** np.arange(len(ep[REWARDS]))
             * ep[REWARDS]).sum() for ep in eps]))
        return {"v_behavior": v_beh,
                "v_target": float(values.mean()),
                "episodes": len(eps)}
