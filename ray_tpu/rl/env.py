"""Environment API + built-in envs.

Reference: `rllib/env/` (BaseEnv/VectorEnv/MultiAgentEnv over gym). The
image has no gym, so the Env protocol is defined here (gymnasium-style
reset/step returning (obs, info) / (obs, reward, terminated, truncated,
info)); external gym envs plug in via `GymEnvAdapter` when available.
CartPole is implemented natively as the standard test/bench workload.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Space:
    def sample(self, rng: np.random.RandomState):
        raise NotImplementedError


class Discrete(Space):
    def __init__(self, n: int):
        self.n = n
        self.shape = ()
        self.dtype = np.int64

    def sample(self, rng):
        return int(rng.randint(self.n))


class Box(Space):
    def __init__(self, low, high, shape=None, dtype=np.float32):
        self.low = np.broadcast_to(np.asarray(low, dtype), shape).copy() \
            if shape else np.asarray(low, dtype)
        self.high = np.broadcast_to(np.asarray(high, dtype), shape).copy() \
            if shape else np.asarray(high, dtype)
        self.shape = self.low.shape
        self.dtype = dtype

    def sample(self, rng):
        return rng.uniform(
            np.clip(self.low, -10, 10),
            np.clip(self.high, -10, 10)).astype(self.dtype)


class Env:
    observation_space: Space
    action_space: Space

    def reset(self, *, seed: Optional[int] = None) -> Tuple[Any, dict]:
        raise NotImplementedError

    def step(self, action) -> Tuple[Any, float, bool, bool, dict]:
        raise NotImplementedError

    def close(self):
        pass


class CartPoleEnv(Env):
    """Classic control CartPole-v1 dynamics (standard constants)."""

    def __init__(self, max_steps: int = 500):
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.max_steps = max_steps
        high = np.array([self.x_threshold * 2, np.inf,
                         self.theta_threshold * 2, np.inf], np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Discrete(2)
        self._rng = np.random.RandomState()
        self._state = None
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(
            np.float32)
        self._t = 0
        return self._state.copy(), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta
                ) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2
                           / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta \
            / self.total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._t += 1
        terminated = bool(abs(x) > self.x_threshold
                          or abs(theta) > self.theta_threshold)
        truncated = self._t >= self.max_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class StatelessCartPoleEnv(CartPoleEnv):
    """CartPole with the velocity components MASKED from the observation
    (obs = [x, theta] only) — the classic partially-observable recurrent
    benchmark (reference: `rllib/examples/env/stateless_cartpole.py`).
    A memoryless policy cannot estimate velocities; a recurrent one
    (R2D2) can, so this env separates the two."""

    def __init__(self, max_steps: int = 200):
        super().__init__(max_steps)
        high = np.array([self.x_threshold * 2,
                         self.theta_threshold * 2], np.float32)
        self.observation_space = Box(-high, high)

    def _mask(self, obs):
        return obs[[0, 2]]

    def reset(self, *, seed: Optional[int] = None):
        obs, info = super().reset(seed=seed)
        return self._mask(obs), info

    def step(self, action):
        obs, r, term, trunc, info = super().step(action)
        return self._mask(obs), r, term, trunc, info


class MemoryCueEnv(Env):
    """T-maze-style memory task (the classic recurrent-policy probe,
    reference: `rllib/examples/env/` memory envs + the R2D2 paper's
    motivation). A binary cue is visible ONLY at t=0; the episode pays
    +1 iff the action taken at the LAST step matches the cue. A
    memoryless policy can do no better than 0.5 in expectation; a
    recurrent policy that carries the cue through its hidden state
    scores 1.0. Obs = [cue0, cue1, progress]."""

    def __init__(self, length: int = 8):
        self.length = length
        self.observation_space = Box(0.0, 1.0, shape=(3,))
        self.action_space = Discrete(2)
        self._rng = np.random.RandomState()
        self._cue = 0
        self._t = 0

    def _obs(self):
        o = np.zeros(3, np.float32)
        if self._t == 0:
            o[self._cue] = 1.0
        o[2] = self._t / self.length
        return o

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._cue = int(self._rng.randint(2))
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        last = self._t >= self.length - 1
        reward = float(int(action) == self._cue) if last else 0.0
        self._t += 1
        return self._obs(), reward, last, False, {}


class PendulumEnv(Env):
    """Classic control Pendulum-v1 dynamics (standard constants) — the
    continuous-action test/bench workload (reference: gym pendulum, used
    by RLlib's SAC/DDPG tuned examples)."""

    def __init__(self, max_steps: int = 200):
        self.max_speed = 8.0
        self.max_torque = 2.0
        self.dt = 0.05
        self.g, self.m, self.l = 10.0, 1.0, 1.0
        self.observation_space = Box(
            np.array([-1.0, -1.0, -self.max_speed], np.float32),
            np.array([1.0, 1.0, self.max_speed], np.float32))
        self.action_space = Box(np.array([-self.max_torque], np.float32),
                                np.array([self.max_torque], np.float32))
        self.max_steps = max_steps
        self._rng = np.random.RandomState()
        self._state = None
        self._t = 0

    def _obs(self):
        th, thdot = self._state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        th, thdot = self._state
        u = float(np.clip(np.asarray(action).ravel()[0],
                          -self.max_torque, self.max_torque))
        angle = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = angle ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.l) * np.sin(th)
                         + 3.0 / (self.m * self.l ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        self._state = (th, thdot)
        self._t += 1
        return self._obs(), -float(cost), False, self._t >= self.max_steps, {}


class CatchPixelsEnv(Env):
    """Pixel-observation Catch (bsuite-style): a ball falls one row per
    step; a 3-px paddle on the bottom row moves left/stay/right; terminal
    reward +1 if caught, -1 if missed. Observations are the rendered
    ``size x size x 1`` float32 frame — the standard cheap pixel env that
    gives a CNN policy real conv FLOPs without an Atari dependency
    (reference pixel envs come from ale-py, absent in this image)."""

    def __init__(self, size: int = 40):
        # Episodes are fixed-length (the ball falls size-1 rows), so
        # there is no separate max_steps knob.
        self.size = size
        # uint8 frames (Atari convention): 4x less worker->learner pipe
        # traffic and host->HBM transfer than float32; the conv torso
        # rescales integer inputs to [0, 1] on device.
        self.observation_space = Box(0, 255, (size, size, 1), np.uint8)
        self.action_space = Discrete(3)
        self._rng = np.random.RandomState()
        self._state = (0, 0, size // 2)  # ball_row, ball_col, paddle_col

    def _render(self):
        s = self.size
        row, col, pad = self._state
        frame = np.zeros((s, s, 1), np.uint8)
        frame[row, col, 0] = 255
        frame[s - 1, max(0, pad - 1):pad + 2, 0] = 128
        return frame

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = (0, int(self._rng.randint(self.size)),
                       self.size // 2)
        return self._render(), {}

    def step(self, action):
        row, col, pad = self._state
        pad = int(np.clip(pad + int(action) - 1, 1, self.size - 2))
        row += 1
        terminated = row >= self.size - 1
        reward = 0.0
        if terminated:
            reward = 1.0 if abs(col - pad) <= 1 else -1.0
        self._state = (min(row, self.size - 1), col, pad)
        return self._render(), reward, terminated, False, {}


class MultiAgentEnv(Env):
    """Multi-agent env protocol (reference `rllib/env/multi_agent_env.py`):
    reset/step consume and return dicts keyed by agent id; the special
    "__all__" key in the terminated/truncated dicts ends the episode."""

    agent_ids: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class GymEnvAdapter(Env):  # pragma: no cover - needs gym installed
    def __init__(self, gym_env):
        self._env = gym_env
        self.observation_space = gym_env.observation_space
        self.action_space = gym_env.action_space

    def reset(self, *, seed=None):
        return self._env.reset(seed=seed)

    def step(self, action):
        return self._env.step(action)


_ENV_REGISTRY: Dict[str, Callable[..., Env]] = {
    "CartPole-v1": CartPoleEnv,
    "StatelessCartPole-v0": StatelessCartPoleEnv,
    "MemoryCue-v0": MemoryCueEnv,
    "Pendulum-v1": PendulumEnv,
    "CatchPixels-v0": CatchPixelsEnv,
}


def register_env(name: str, creator: Callable[..., Env]):
    """Reference: `ray.tune.registry.register_env`."""
    _ENV_REGISTRY[name] = creator


def unregister_env(name: str) -> None:
    """Remove a registered env creator (tests registering throwaway
    envs must be able to take them back out; raylint R7)."""
    _ENV_REGISTRY.pop(name, None)


def make_env(spec, env_config: Optional[dict] = None) -> Env:
    if isinstance(spec, Env):
        return spec
    if callable(spec):
        return spec(env_config or {})
    if isinstance(spec, str):
        if spec in _ENV_REGISTRY:
            try:
                return _ENV_REGISTRY[spec](**(env_config or {}))
            except TypeError:
                return _ENV_REGISTRY[spec](env_config or {})
        try:
            import gymnasium

            return GymEnvAdapter(gymnasium.make(spec))
        except ImportError:
            raise ValueError(f"unknown env {spec!r} and gymnasium not "
                             "installed")
        except Exception as e:  # gymnasium registry miss -> uniform error
            raise ValueError(f"unknown env {spec!r}: {e}") from e
    raise TypeError(f"cannot build env from {spec!r}")


class CartPoleVectorEnv:
    """Batched-numpy CartPole: all N envs step in one vectorized update
    (the rollout hot loop — reference rollout workers rely on C-speed
    gym envs; this is the numpy equivalent). Same auto-reset + final_obs
    contract as VectorEnv."""

    def __init__(self, num_envs: int, max_steps: int = 500):
        proto = CartPoleEnv(max_steps)
        self.observation_space = proto.observation_space
        self.action_space = proto.action_space
        self.num_envs = num_envs
        self.max_steps = max_steps
        self._p = proto
        self._state = np.zeros((num_envs, 4), np.float64)
        self._t = np.zeros(num_envs, np.int64)
        self._rng = np.random.RandomState()

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, (self.num_envs, 4))
        self._t[:] = 0
        return self._state.astype(np.float32).copy()

    def _reset_rows(self, rows):
        self._state[rows] = self._rng.uniform(-0.05, 0.05,
                                              (len(rows), 4))
        self._t[rows] = 0

    def step(self, actions):
        p = self._p
        x, x_dot, th, th_dot = self._state.T
        force = np.where(np.asarray(actions) == 1, p.force_mag,
                         -p.force_mag)
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + p.polemass_length * th_dot ** 2 * sin) \
            / p.total_mass
        th_acc = (p.gravity * sin - cos * temp) / (
            p.length * (4.0 / 3.0 - p.masspole * cos ** 2
                        / p.total_mass))
        x_acc = temp - p.polemass_length * th_acc * cos / p.total_mass
        x = x + p.tau * x_dot
        x_dot = x_dot + p.tau * x_acc
        th = th + p.tau * th_dot
        th_dot = th_dot + p.tau * th_acc
        self._state = np.stack([x, x_dot, th, th_dot], axis=1)
        self._t += 1
        terms = (np.abs(x) > p.x_threshold) \
            | (np.abs(th) > p.theta_threshold)
        truncs = (self._t >= self.max_steps) & ~terms
        self.final_obs = self._state.astype(np.float32).copy()
        done_rows = np.nonzero(terms | truncs)[0]
        if len(done_rows):
            self._reset_rows(done_rows)
        return (self._state.astype(np.float32).copy(),
                np.ones(self.num_envs, np.float32), terms, truncs)


class CatchPixelsVectorEnv:
    """Batched-numpy CatchPixels: all N frames render in one pass (the
    pixel-env rollout hot loop). Same auto-reset + final_obs contract as
    VectorEnv."""

    def __init__(self, num_envs: int, size: int = 40):
        proto = CatchPixelsEnv(size)
        self.observation_space = proto.observation_space
        self.action_space = proto.action_space
        self.num_envs = num_envs
        self.size = size
        self._row = np.zeros(num_envs, np.int64)
        self._col = np.zeros(num_envs, np.int64)
        self._pad = np.full(num_envs, size // 2, np.int64)
        self._rng = np.random.RandomState()

    def _render(self) -> np.ndarray:
        n, s = self.num_envs, self.size
        frames = np.zeros((n, s, s, 1), np.uint8)
        ar = np.arange(n)
        frames[ar, self._row, self._col, 0] = 255
        for off in (-1, 0, 1):
            frames[ar, s - 1, np.clip(self._pad + off, 0, s - 1), 0] = 128
        return frames

    def _reset_rows(self, rows):
        self._row[rows] = 0
        self._col[rows] = self._rng.randint(self.size, size=len(rows))
        self._pad[rows] = self.size // 2

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._reset_rows(np.arange(self.num_envs))
        return self._render()

    def step(self, actions):
        self._pad = np.clip(self._pad + np.asarray(actions) - 1, 1,
                            self.size - 2)
        self._row += 1
        terms = self._row >= self.size - 1
        rewards = np.where(
            terms,
            np.where(np.abs(self._col - self._pad) <= 1, 1.0, -1.0),
            0.0).astype(np.float32)
        self._row = np.minimum(self._row, self.size - 1)
        frame = self._render()  # pre-reset: the true successor obs
        self.final_obs = frame
        done_rows = np.nonzero(terms)[0]
        if len(done_rows):
            self._reset_rows(done_rows)
            obs = self._render()
        else:
            obs = frame
        truncs = np.zeros(self.num_envs, bool)
        return obs, rewards, terms, truncs


class VectorEnv:
    """N envs behind a batched interface (reference
    `rllib/env/vector_env.py`). Built-in envs with a vectorized
    implementation (CartPole, CatchPixels) step as one numpy update;
    everything else steps sequentially."""

    def __new__(cls, spec, num_envs: int,
                env_config: Optional[dict] = None):
        if spec == "CartPole-v1" and not env_config:
            return CartPoleVectorEnv(num_envs)
        if spec == "CatchPixels-v0" and \
                set(env_config or {}) <= {"size"}:
            return CatchPixelsVectorEnv(num_envs, **(env_config or {}))
        return super().__new__(cls)

    def __init__(self, spec, num_envs: int,
                 env_config: Optional[dict] = None):
        self.envs: List[Env] = [make_env(spec, env_config)
                                for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space

    def reset(self, *, seed: Optional[int] = None):
        obs = []
        for i, e in enumerate(self.envs):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions):
        obs, final, rews, terms, truncs = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, te, tr, _ = e.step(a)
            final.append(o)  # the true successor obs, pre-reset
            if te or tr:
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(te)
            truncs.append(tr)
        # Auto-reset swallows the episode's real final observation from
        # the return value; keep it reachable so off-policy algorithms
        # can bootstrap truncated episodes correctly (gymnasium puts it
        # in info["final_observation"]; here it's a property).
        self.final_obs = np.stack(final)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs))
