"""Learner / LearnerGroup / LearnerThread — the new-stack learner scaling
layer (reference: `rllib/core/learner/learner.py:89`,
`rllib/core/learner/learner_group.py:51`,
`rllib/execution/learner_thread.py:1`).

TPU-first redesign rather than a port of the torch-DDP pattern:

- A `Learner` owns policy/optimizer state and ONE pure, jit-compiled
  ``step_fn(state, batch) -> (state, stats)`` covering loss, gradients,
  gradient sync, and the optimizer apply. Target-network cadences and
  similar bookkeeping live inside the program as `extra` state, so a
  learner update is a single dispatch with no host round-trips.
- Sharded learning ("DDP") is not N processes exchanging gradients: on a
  `jax.sharding.Mesh` the SAME compiled program runs over all devices
  with the batch sharded on the `data` axis and parameters replicated —
  XLA inserts the gradient all-reduce over ICI. `LearnerGroup(mesh=...)`
  is therefore the primary scaling mode on a TPU slice.
- `LearnerGroup(num_learners=N)` additionally covers the reference's
  actor-sharded mode (multi-host without jax.distributed): N learner
  actors each grad their batch shard, all-reduce gradients through
  `ray_tpu.util.collective`, and apply identically.
- `LearnerThread` runs updates continuously on-device while rollout
  actors keep sampling — the IMPALA/APPO async pattern — and accounts
  device-busy vs queue-starved time honestly (windows are closed by a
  host scalar fetch; `block_until_ready` is not a reliable barrier on
  every platform).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu


def _tree_size(tree) -> int:
    return sum(np.asarray(x).size for x in jax.tree_util.tree_leaves(tree))


class Learner:
    """Owns (params, opt_state, extra) and a pure compiled step.

    Built either from a full ``step_fn`` (algorithms with bespoke updates)
    or from a ``loss_fn`` via :meth:`from_loss` (which also unlocks
    ``compute_gradients``/``apply_gradients`` for actor-sharded DDP —
    reference `learner.py:275,286`).

    Args:
        step_fn: pure ``(state, batch) -> (state, stats)`` where state is
            the dict ``{"params", "opt_state", "extra"}``.
        state: initial state dict (``extra`` may be None).
        mesh: optional `jax.sharding.Mesh`; when given the step is
            compiled with the batch sharded over ``data_axis`` (leading
            dim of every batch leaf) and state replicated — XLA performs
            the gradient reduction.
        loss_fn / tx: retained when constructed via from_loss, enabling
            the gradient-level API.
    """

    def __init__(self, step_fn: Callable, state: Dict[str, Any], *,
                 mesh=None, data_axis: str = "data",
                 loss_fn: Optional[Callable] = None, tx=None):
        self._raw_step = step_fn
        self.state = dict(state)
        self.state.setdefault("extra", None)
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.tx = tx
        self._lock = threading.Lock()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(mesh, P())
            batch_sh = NamedSharding(mesh, P(data_axis))
            self._step = jax.jit(
                step_fn,
                in_shardings=(replicated, batch_sh),
                out_shardings=(replicated, replicated),
                donate_argnums=(0,))
        else:
            self._step = jax.jit(step_fn, donate_argnums=(0,))
        if loss_fn is not None:
            self._grad = jax.jit(
                jax.value_and_grad(loss_fn, has_aux=True))
            self._apply = jax.jit(self._apply_fn, donate_argnums=(0,))

    # -- construction ----------------------------------------------------

    @classmethod
    def from_loss(cls, loss_fn: Callable, params, tx, *, mesh=None,
                  data_axis: str = "data") -> "Learner":
        """Build the canonical step (value_and_grad → tx.update → apply)
        from a ``loss_fn(params, batch) -> (loss, stats)``."""
        import optax

        def step_fn(state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state["params"], batch)
            updates, opt_state = tx.update(grads, state["opt_state"],
                                           state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            stats = dict(stats)
            stats.setdefault("loss", loss)
            return ({"params": new_params, "opt_state": opt_state,
                     "extra": state["extra"]}, stats)

        state = {"params": params, "opt_state": tx.init(params),
                 "extra": None}
        return cls(step_fn, state, mesh=mesh, data_axis=data_axis,
                   loss_fn=loss_fn, tx=tx)

    def _apply_fn(self, state, grads):
        import optax

        updates, opt_state = self.tx.update(grads, state["opt_state"],
                                            state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt_state": opt_state,
                "extra": state["extra"]}

    # -- update API ------------------------------------------------------

    def update(self, batch) -> Dict[str, Any]:
        """One full update; returns the (device-resident) stats pytree."""
        if isinstance(batch, dict):
            # jnp.asarray is a no-op for arrays already on device — do
            # NOT round-trip them through numpy (LearnerThread converts
            # once and reuses the device batch num_sgd_iter times).
            batch = {k: v if isinstance(v, jax.Array) else
                     jnp.asarray(np.asarray(v))
                     for k, v in batch.items()}
        with self._lock:
            self.state, stats = self._step(self.state, batch)
        return stats

    def compute_gradients(self, batch):
        """Gradients on THIS learner's batch shard (no apply) — the
        actor-sharded DDP half-step. Requires from_loss construction."""
        assert self.loss_fn is not None, \
            "compute_gradients needs a loss_fn-built Learner"
        batch = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()}
        (loss, stats), grads = self._grad(self.state["params"], batch)
        stats = dict(stats)
        stats.setdefault("loss", loss)
        return grads, stats

    def apply_gradients(self, grads):
        with self._lock:
            self.state = self._apply(self.state, grads)

    # -- weights / state -------------------------------------------------

    def get_weights(self):
        # Host copies, fetched under the lock: the step donates its
        # input state, so returning live device buffers would hand the
        # caller arrays the next update invalidates.
        with self._lock:
            return jax.device_get(self.state["params"])

    def set_weights(self, weights, reset_optimizer: bool = False):
        with self._lock:
            self.state["params"] = jax.tree.map(jnp.asarray, weights)
            if reset_optimizer and self.tx is not None:
                self.state["opt_state"] = self.tx.init(
                    self.state["params"])

    def get_state(self):
        with self._lock:
            return jax.device_get(self.state)

    def set_state(self, state):
        with self._lock:
            self.state = jax.tree.map(jnp.asarray, state)


@ray_tpu.remote
class _LearnerActor:
    """One shard of an actor-sharded LearnerGroup (reference
    `learner_group.py` remote workers). Gradients sync through
    `ray_tpu.util.collective` (host all-reduce); every shard then applies
    the same mean gradient, so parameters never drift."""

    def __init__(self, build_learner, rank: int, world: int,
                 group_name: str):
        self.learner: Learner = build_learner()
        self.rank, self.world, self.group = rank, world, group_name
        if world > 1:
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank,
                                             group_name=group_name)

    def update_shard(self, batch) -> Dict[str, Any]:
        grads, stats = self.learner.compute_gradients(batch)
        if self.world > 1:
            from ray_tpu.util import collective

            # One flat vector -> one collective (rides the sharded
            # allreduce path for big gradients).
            leaves, treedef = jax.tree_util.tree_flatten(
                jax.device_get(grads))
            vec = np.concatenate(
                [np.asarray(g, np.float32).ravel() for g in leaves])
            mean = collective.allreduce(
                vec, group_name=self.group) / self.world
            out, off = [], 0
            for g in leaves:
                out.append(jnp.asarray(
                    mean[off:off + g.size].reshape(g.shape), g.dtype))
                off += g.size
            grads = jax.tree_util.tree_unflatten(treedef, out)
        self.learner.apply_gradients(grads)
        return {k: float(np.asarray(jax.device_get(v)))
                for k, v in stats.items()}

    def get_weights(self):
        return jax.device_get(self.learner.get_weights())

    def set_weights(self, w, reset_optimizer: bool = False):
        self.learner.set_weights(w, reset_optimizer=reset_optimizer)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, s):
        self.learner.set_state(s)


class LearnerGroup:
    """Coordinator of one local (possibly mesh-sharded) Learner or N
    learner actors (reference `learner_group.py:51`).

    ``num_learners=0`` — local mode: a single in-process Learner; pass
    ``mesh`` to shard the batch across devices inside the program (the
    TPU-slice scaling path; multi-chip DDP with zero host traffic).
    ``num_learners>=1`` — actor mode: the batch splits into N shards
    along its leading axis; actors grad, all-reduce, apply.
    """

    def __init__(self, *, build_learner: Optional[Callable] = None,
                 learner: Optional[Learner] = None, num_learners: int = 0,
                 group_name: Optional[str] = None):
        self.num_learners = num_learners
        if num_learners <= 0:
            self._learner = learner if learner is not None \
                else build_learner()
            self._actors = None
        else:
            assert build_learner is not None, \
                "actor mode needs a picklable build_learner"
            name = group_name or f"learner_group_{id(self):x}"
            self._learner = None
            self._actors = [
                _LearnerActor.remote(build_learner, i, num_learners, name)
                for i in range(num_learners)
            ]
            # Fail fast on construction errors (actor init is async).
            ray_tpu.get([a.get_weights.remote() for a in self._actors])

    @property
    def is_local(self) -> bool:
        return self._actors is None

    def update(self, batch) -> Dict[str, float]:
        """One synchronous update over the full batch; returns scalar
        stats (mean across shards in actor mode)."""
        if self._actors is None:
            stats = self._learner.update(batch)
            return {k: float(np.asarray(jax.device_get(v)))
                    for k, v in stats.items()}
        shards = self._shard_batch(batch, len(self._actors))
        all_stats = ray_tpu.get([
            a.update_shard.remote(s)
            for a, s in zip(self._actors, shards)])
        return {k: float(np.mean([s[k] for s in all_stats]))
                for k in all_stats[0]}

    @staticmethod
    def _shard_batch(batch, n: int) -> List[dict]:
        keys = list(batch.keys())
        size = len(np.asarray(batch[keys[0]]))
        idx = np.array_split(np.arange(size), n)
        return [{k: np.asarray(batch[k])[ix] for k in keys}
                for ix in idx]

    def get_weights(self):
        if self._actors is None:
            return self._learner.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, w, reset_optimizer: bool = False):
        if self._actors is None:
            self._learner.set_weights(w, reset_optimizer=reset_optimizer)
        else:
            ray_tpu.get([a.set_weights.remote(w, reset_optimizer)
                         for a in self._actors])

    def get_state(self):
        if self._actors is None:
            return self._learner.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, s):
        if self._actors is None:
            self._learner.set_state(s)
        else:
            ray_tpu.get([a.set_state.remote(s) for a in self._actors])

    def shutdown(self):
        if self._actors:
            for a in self._actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            self._actors = None


class LearnerThread(threading.Thread):
    """Continuous on-device learning decoupled from sampling (reference
    `rllib/execution/learner_thread.py`): rollout futures feed
    :meth:`put`; this thread drains the queue and updates; each queued
    batch is reused ``num_sgd_iter`` times (the reference's minibatch
    buffer). Stats separate device-busy from queue-starved wall time —
    the round-3 verdict's "is the TPU actually working?" number.
    """

    def __init__(self, learner: Learner, *, in_queue_size: int = 8,
                 num_sgd_iter: int = 1, barrier_every: int = 8):
        super().__init__(daemon=True, name="learner-thread")
        self.learner = learner
        self.inq: "queue.Queue" = queue.Queue(maxsize=in_queue_size)
        self.num_sgd_iter = max(1, num_sgd_iter)
        self.barrier_every = max(1, barrier_every)
        self._stop_evt = threading.Event()
        self._t_start = None
        # telemetry (reader: training_step / bench)
        self.samples_consumed = 0
        self.updates = 0
        self.busy_s = 0.0
        self.starved_s = 0.0
        self.last_stats: Dict[str, float] = {}
        self._window_updates = 0
        self._window_t0 = None
        self._window_starved = 0.0
        self._pending_stats = None
        self._flush_req: Optional[threading.Event] = None
        # A crashed update must surface at the feeder, not wedge it: the
        # thread records the error and producers see it on put().
        self.error: Optional[BaseException] = None

    # -- producer side ---------------------------------------------------

    def put(self, batch, block: bool = True, timeout=None):
        """Enqueue one sampled batch (blocking = backpressure on the
        sampling side, reference learner queue semantics). Raises the
        learner's own failure instead of blocking on a dead thread."""
        if self.error is not None:
            raise RuntimeError("learner thread died") from self.error
        self.inq.put(batch, block=block, timeout=timeout)

    def get_weights(self):
        return self.learner.get_weights()

    def flush_windows(self, timeout: float = 30.0) -> None:
        """Close the current busy-accounting window at the next safe
        point ON the learner thread and wait for it. Benchmarks call
        this at both measurement boundaries so busy-time deltas line up
        with the measured wall: a window opened before the measurement
        (e.g. spanning warm-up compile time) can otherwise bank its
        whole busy span *inside* the measurement and push
        device_busy_fraction past 1.0."""
        if self.is_alive():
            evt = threading.Event()
            self._flush_req = evt
            deadline = time.perf_counter() + timeout
            # Poll liveness: a thread that crashes after the check
            # above must not pin the caller for the full timeout (its
            # exit path services the request, but belt and braces).
            while not evt.wait(0.05):
                if not self.is_alive() or \
                        time.perf_counter() > deadline:
                    break
            if evt.is_set():
                return
            self._flush_req = None
            if self.is_alive():
                return  # wedged mid-update: flush is best-effort
        # Thread exited (stopped or crashed): no concurrent access,
        # close any leftover window directly.
        if self._window_updates:
            self._close_window()

    def _maybe_flush(self):
        req = self._flush_req
        if req is not None:
            self._flush_req = None
            self._close_window()
            req.set()

    # -- thread body -----------------------------------------------------

    def run(self):
        try:
            self._run_inner()
        finally:
            # Whatever the exit path (stop, crash): bank the leftover
            # window and release any flush_windows() waiter — a crashed
            # learner must not pin the bench/caller for its timeout.
            if self._window_updates:
                try:
                    self._close_window()
                except Exception:
                    pass
            req = self._flush_req
            if req is not None:
                self._flush_req = None
                req.set()

    def _run_inner(self):
        self._t_start = time.perf_counter()
        self._window_t0 = self._t_start
        while not self._stop_evt.is_set():
            self._maybe_flush()
            t0 = time.perf_counter()
            try:
                batch = self.inq.get(timeout=0.2)
            except queue.Empty:
                self._window_starved += time.perf_counter() - t0
                self.starved_s += time.perf_counter() - t0
                continue
            waited = time.perf_counter() - t0
            self._window_starved += waited
            self.starved_s += waited
            lead = np.asarray(batch[next(iter(batch))])
            # batches are [N, T, ...] fragments: N*T transitions each
            transitions = int(lead.shape[0] * lead.shape[1]) \
                if lead.ndim >= 2 else int(lead.shape[0])
            try:
                batch_j = {k: jnp.asarray(np.asarray(v))
                           for k, v in batch.items()}
                for _ in range(self.num_sgd_iter):
                    self._pending_stats = self.learner.update(batch_j)
                    self.updates += 1
                    self._window_updates += 1
                    self.samples_consumed += transitions
                    if self._window_updates >= self.barrier_every:
                        self._close_window()
                    else:
                        self._maybe_flush()
            except BaseException as e:  # noqa: BLE001 — surfaced at put()
                self.error = e
                return

    def _close_window(self):
        """Fetch one host scalar — the only trustworthy completion
        barrier — and bank the window's device-busy time."""
        stats = self._pending_stats or {}
        key = "loss" if "loss" in stats else next(iter(stats), None)
        if key is not None:
            self.last_stats = {key: float(np.asarray(
                jax.device_get(stats[key])))}
        t1 = time.perf_counter()
        self.busy_s += (t1 - self._window_t0) - self._window_starved
        self._window_t0 = t1
        self._window_starved = 0.0
        self._window_updates = 0

    # -- telemetry -------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        wall = (time.perf_counter() - self._t_start) \
            if self._t_start else 0.0
        return {
            "learner_updates": self.updates,
            "learner_samples_consumed": self.samples_consumed,
            "learner_busy_s": round(self.busy_s, 3),
            "learner_starved_s": round(self.starved_s, 3),
            "device_busy_fraction":
                round(self.busy_s / wall, 4) if wall else 0.0,
            "learner_queue_len": self.inq.qsize(),
            **{f"last_{k}": v for k, v in self.last_stats.items()},
        }

    def stop(self, join: bool = True):
        self._stop_evt.set()
        if join and self.is_alive():
            self.join(timeout=30)
