"""RolloutWorker: actor-side env stepping.

Reference: `rllib/evaluation/rollout_worker.py` + `sampler.py` — workers
hold env copies + policy weights, sample fixed-size trajectory fragments,
and sync weights from the learner (broadcast through the object store).
Policy inference on workers is CPU jax (batched over the vector env).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import VectorEnv
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    STATE_IN,
    SampleBatch,
    TERMINATEDS,
    VALUES,
)


@ray_tpu.remote
class RolloutWorker:
    def __init__(self, env_spec, policy_apply: Callable, *,
                 num_envs: int = 1, env_config: Optional[dict] = None,
                 rollout_fragment_length: int = 200, seed: int = 0,
                 policy_kind: str = "actor_critic",
                 obs_connectors=None, action_connectors=None,
                 inference_device: str = "cpu",
                 state_size: int = 0,
                 append_prev_action: bool = False):
        import jax

        self.vec = VectorEnv(env_spec, num_envs, env_config)
        # Rollout inference runs on the HOST by default (reference:
        # rollout workers are CPU actors; the accelerator belongs to the
        # learner). Without the pin, every worker's per-step policy call
        # would dispatch to the default backend — on a TPU host that
        # means N processes contending for the chip against the learner.
        try:
            self._dev = jax.devices(inference_device)[0]
        except RuntimeError:
            self._dev = None
        self.apply = jax.jit(policy_apply)
        self.fragment = rollout_fragment_length
        self.kind = policy_kind
        # Connector pipelines (ray_tpu.rl.connectors): obs transforms run
        # before the policy (and the transformed obs is what lands in the
        # batch, so the learner sees the same space); action transforms
        # run between the policy sample and env.step. Stateful connector
        # state (e.g. NormalizeObs running stats) is worker-local.
        self.obs_connectors = obs_connectors
        if policy_kind == "gaussian" and action_connectors is None:
            # Gaussian policies emit squashed actions in [-1, 1]; the
            # default pipeline rescales to the action-space bounds. A
            # caller-supplied pipeline REPLACES this (so composing your
            # own UnsquashAction doesn't double-rescale).
            from ray_tpu.rl.connectors import (ConnectorPipeline,
                                               UnsquashAction)

            space = self.vec.action_space
            action_connectors = ConnectorPipeline(
                [UnsquashAction(space.low, space.high)])
        self.action_connectors = action_connectors
        self._rng = np.random.RandomState(seed)
        self._jax_rng = jax.random.PRNGKey(seed)
        self.obs = self._connect_obs(self.vec.reset(seed=seed))
        self._episode_rewards = np.zeros(num_envs, np.float64)
        self._episode_lens = np.zeros(num_envs, np.int64)
        self._completed: list = []
        # Recurrent policies (kind="recurrent") carry a hidden state per
        # env across sample() calls; zeroed on episode boundaries
        # (reference: RLlib's view-requirement state columns).
        self._hidden: Optional[np.ndarray] = (
            np.zeros((num_envs, state_size), np.float32)
            if policy_kind == "recurrent" else None)
        # R2D2-style input augmentation: append [one-hot(prev action),
        # prev reward] to the observation the recurrent policy (and the
        # recorded OBS/NEXT_OBS columns) sees. Gives the GRU the action
        # history it needs to deduce latent state (e.g. velocities) in
        # partially-observable envs (Kapturowski et al. 2019 §2.3).
        self._prev: Optional[np.ndarray] = None
        if append_prev_action:
            n_act = self.vec.action_space.n
            self._prev = np.zeros((num_envs, n_act + 1), np.float32)

    def sample(self, weights) -> SampleBatch:
        """Collect one fragment of `fragment` steps × num_envs."""
        import contextlib

        import jax

        ctx = jax.default_device(self._dev) if self._dev is not None \
            else contextlib.nullcontext()
        with ctx:
            return self._sample(weights)

    def _sample(self, weights) -> SampleBatch:
        rows: Dict[str, list] = {OBS: [], ACTIONS: [], REWARDS: [],
                                 DONES: [], TERMINATEDS: [], NEXT_OBS: [],
                                 LOGPS: [], VALUES: []}
        if self.kind == "recurrent":
            rows[STATE_IN] = []
        for _ in range(self.fragment):
            if self.kind == "recurrent":
                obs_in = (self.obs if self._prev is None else
                          np.concatenate([self.obs, self._prev], -1)
                          .astype(np.float32))
                rows[STATE_IN].append(self._hidden.copy())
                out, h_next = self.apply(weights, obs_in, self._hidden)
                self._hidden = np.array(h_next, np.float32)  # writable copy
            else:
                obs_in = self.obs
                out = self.apply(weights, self.obs)
            if self.kind == "gaussian":
                # Continuous control: tanh-squashed diagonal Gaussian.
                # ACTIONS stores the squashed action in [-1, 1]; the
                # action-connector pipeline (UnsquashAction installed by
                # default in __init__) rescales for the env.
                mean, log_std = (np.asarray(o, np.float32) for o in out)
                std = np.exp(log_std)
                u = mean + std * self._rng.normal(size=mean.shape)
                actions = np.tanh(u)
                act_logp = (-0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                                    + np.log(2 * np.pi))).sum(-1)
                act_logp -= (2 * (np.log(2.0) - u
                                  - _softplus(-2 * u))).sum(-1)
                values = np.zeros(len(self.obs), np.float32)
                env_actions = actions
            else:
                if self.kind == "actor_critic":
                    logits, values = out
                else:  # q-network: epsilon handled by caller config
                    logits, values = out, np.zeros(len(self.obs),
                                                   np.float32)
                logits = np.asarray(logits, np.float32)
                # Sample actions from the categorical distribution.
                z = self._rng.gumbel(size=logits.shape)
                actions = (logits + z).argmax(-1)
                logp = logits - _logsumexp(logits)
                act_logp = np.take_along_axis(
                    logp, actions[:, None], axis=1)[:, 0]
                env_actions = actions
            if self.action_connectors is not None:
                env_actions = self.action_connectors(env_actions)
            next_obs, rewards, terms, truncs = self.vec.step(env_actions)
            dones = np.logical_or(terms, truncs)
            if self._hidden is not None and dones.any():
                self._hidden[dones] = 0.0
            if dones.any():
                # NEXT_OBS must be the true successor (pre-auto-reset) so
                # off-policy targets bootstrap truncated episodes right;
                # the policy continues from the post-reset obs. (Both go
                # through the obs connectors; stateful connector stats
                # see done-step rows twice — negligible.)
                true_next = self._connect_obs(self.vec.final_obs)
                next_obs = self._connect_obs(next_obs)
            else:
                next_obs = true_next = self._connect_obs(next_obs)
            if self._prev is not None:
                # The successor frame's "previous action/reward" is this
                # step's — record NEXT_OBS augmented the same way the
                # policy will see it, then roll the memory (zeroed at
                # episode starts: a fresh episode has no history).
                next_prev = np.zeros_like(self._prev)
                next_prev[np.arange(len(actions)), actions] = 1.0
                next_prev[:, -1] = rewards
                true_next = np.concatenate(
                    [true_next, next_prev], -1).astype(np.float32)
                self._prev = next_prev.copy()
                self._prev[dones] = 0.0
            rows[OBS].append(np.array(obs_in, np.float32)
                             if self._prev is not None
                             else self.obs.copy())
            rows[ACTIONS].append(actions)
            rows[REWARDS].append(rewards)
            rows[DONES].append(dones)
            rows[TERMINATEDS].append(np.asarray(terms))
            rows[NEXT_OBS].append(true_next.copy())
            rows[LOGPS].append(act_logp)
            rows[VALUES].append(np.asarray(values, np.float32))
            self._episode_rewards += rewards
            self._episode_lens += 1
            for i in np.nonzero(dones)[0]:
                self._completed.append(
                    (float(self._episode_rewards[i]),
                     int(self._episode_lens[i])))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
            self.obs = next_obs
        # [T, N, ...] -> [T*N, ...] time-major flatten per env kept
        # contiguous: transpose to [N, T, ...] so GAE can scan per env.
        batch = SampleBatch()
        for k, v in rows.items():
            arr = np.stack(v)  # [T, N, ...]
            batch[k] = np.swapaxes(arr, 0, 1)  # [N, T, ...]
        return batch

    def _connect_obs(self, obs):
        return obs if self.obs_connectors is None \
            else self.obs_connectors(obs)

    def connector_state(self):
        return {
            "obs": None if self.obs_connectors is None
            else self.obs_connectors.get_state(),
            "action": None if self.action_connectors is None
            else self.action_connectors.get_state(),
        }

    def set_connector_state(self, state):
        if state.get("obs") and self.obs_connectors is not None:
            self.obs_connectors.set_state(state["obs"])
        if state.get("action") and self.action_connectors is not None:
            self.action_connectors.set_state(state["action"])

    def episode_stats(self, clear: bool = True):
        stats = list(self._completed)
        if clear:
            self._completed = []
        return stats


def _logsumexp(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=axis, keepdims=True))


def _softplus(x):
    return np.logaddexp(0.0, x)
