"""RolloutWorker: actor-side env stepping.

Reference: `rllib/evaluation/rollout_worker.py` + `sampler.py` — workers
hold env copies + policy weights, sample fixed-size trajectory fragments,
and sync weights from the learner (broadcast through the object store).
Policy inference on workers is CPU jax (batched over the vector env).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import VectorEnv, make_env
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
    VALUES,
)


@ray_tpu.remote
class RolloutWorker:
    def __init__(self, env_spec, policy_apply: Callable, *,
                 num_envs: int = 1, env_config: Optional[dict] = None,
                 rollout_fragment_length: int = 200, seed: int = 0,
                 policy_kind: str = "actor_critic"):
        import jax

        self.vec = VectorEnv(env_spec, num_envs, env_config)
        self.apply = jax.jit(policy_apply)
        self.fragment = rollout_fragment_length
        self.kind = policy_kind
        self._rng = np.random.RandomState(seed)
        self._jax_rng = jax.random.PRNGKey(seed)
        self.obs = self.vec.reset(seed=seed)
        self._episode_rewards = np.zeros(num_envs, np.float64)
        self._episode_lens = np.zeros(num_envs, np.int64)
        self._completed: list = []

    def sample(self, weights) -> SampleBatch:
        """Collect one fragment of `fragment` steps × num_envs."""
        import jax

        rows: Dict[str, list] = {OBS: [], ACTIONS: [], REWARDS: [],
                                 DONES: [], NEXT_OBS: [], LOGPS: [],
                                 VALUES: []}
        for _ in range(self.fragment):
            out = self.apply(weights, self.obs)
            if self.kind == "actor_critic":
                logits, values = out
            else:  # q-network: greedy-ish epsilon handled by caller config
                logits, values = out, np.zeros(len(self.obs), np.float32)
            logits = np.asarray(logits, np.float32)
            # Sample actions from the categorical distribution.
            z = self._rng.gumbel(size=logits.shape)
            actions = (logits + z).argmax(-1)
            logp = logits - _logsumexp(logits)
            act_logp = np.take_along_axis(
                logp, actions[:, None], axis=1)[:, 0]
            next_obs, rewards, terms, truncs = self.vec.step(actions)
            dones = np.logical_or(terms, truncs)
            rows[OBS].append(self.obs.copy())
            rows[ACTIONS].append(actions)
            rows[REWARDS].append(rewards)
            rows[DONES].append(dones)
            rows[NEXT_OBS].append(next_obs.copy())
            rows[LOGPS].append(act_logp)
            rows[VALUES].append(np.asarray(values, np.float32))
            self._episode_rewards += rewards
            self._episode_lens += 1
            for i in np.nonzero(dones)[0]:
                self._completed.append(
                    (float(self._episode_rewards[i]),
                     int(self._episode_lens[i])))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
            self.obs = next_obs
        # [T, N, ...] -> [T*N, ...] time-major flatten per env kept
        # contiguous: transpose to [N, T, ...] so GAE can scan per env.
        batch = SampleBatch()
        for k, v in rows.items():
            arr = np.stack(v)  # [T, N, ...]
            batch[k] = np.swapaxes(arr, 0, 1)  # [N, T, ...]
        return batch

    def episode_stats(self, clear: bool = True):
        stats = list(self._completed)
        if clear:
            self._completed = []
        return stats


def _logsumexp(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=axis, keepdims=True))
