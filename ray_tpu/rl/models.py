"""Policy/value networks in JAX (reference: `rllib/models/` catalog).

Small MLP torsos; the TPU story is that the *learner update* is one jit
program (`ray_tpu.rl.learner`) — rollouts stay on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, sizes, dtype=jnp.float32):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (i, o) in zip(keys, zip(sizes[:-1], sizes[1:])):
        params.append({
            "w": jax.random.normal(k, (i, o), dtype) * np.sqrt(2.0 / i),
            "b": jnp.zeros(o, dtype),
        })
    return params


def mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


def actor_critic_init(rng, obs_dim: int, n_actions: int,
                      hidden=(64, 64)) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, (obs_dim, *hidden, n_actions)),
        "vf": mlp_init(k2, (obs_dim, *hidden, 1)),
    }


def actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def q_net_init(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    return {"q": mlp_init(rng, (obs_dim, *hidden, n_actions))}


def q_net_apply(params, obs):
    return mlp_apply(params["q"], obs)


# -- conv torso for pixel observations -------------------------------------
# Reference: `rllib/models/catalog.py` CNN configs (the Atari "nature
# CNN"). NHWC layout + VALID padding so XLA tiles the convs onto the MXU
# without layout shuffles.

_CNN_SPEC = ((32, 8, 4), (64, 4, 2), (64, 3, 1))  # (out_ch, kernel, stride)


def _conv_init(key, k, cin, cout, dtype=jnp.float32):
    fan_in = k * k * cin
    return {
        "w": jax.random.normal(key, (k, k, cin, cout), dtype)
        * np.sqrt(2.0 / fan_in),
        "b": jnp.zeros(cout, dtype),
    }


def _conv_apply(layer, x, stride):
    y = jax.lax.conv_general_dilated(
        x, layer["w"], window_strides=(stride, stride), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return jax.nn.relu(y + layer["b"])


def _cnn_out_dim(hw: int, cnn_spec=_CNN_SPEC) -> int:
    for _, k, s in cnn_spec:
        hw = (hw - k) // s + 1
        assert hw >= 1, "observation too small for the conv stack"
    return hw * hw * cnn_spec[-1][0]


def cnn_actor_critic_init(rng, obs_shape, n_actions: int,
                          hidden: int = 256) -> Dict[str, Any]:
    """Shared conv torso + dense neck, separate pi/vf heads.
    obs_shape = (H, W, C) with H == W."""
    h, w, c = obs_shape
    assert h == w, "square observations only"
    keys = jax.random.split(rng, len(_CNN_SPEC) + 3)
    convs = []
    cin = c
    for key, (cout, k, _) in zip(keys, _CNN_SPEC):
        convs.append(_conv_init(key, k, cin, cout))
        cin = cout
    flat = _cnn_out_dim(h)
    return {
        "conv": convs,
        "neck": mlp_init(keys[-3], (flat, hidden)),
        "pi": mlp_init(keys[-2], (hidden, n_actions)),
        "vf": mlp_init(keys[-1], (hidden, 1)),
    }


def cnn_actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, H, W, C] -> (logits [B, A], value [B]). Integer inputs
    (uint8 frames — shipped that way to quarter the host->HBM traffic)
    rescale to [0, 1] on device; float inputs pass through."""
    x = jnp.asarray(obs)
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(jnp.float32) / 255.0
    for layer, (_, _, stride) in zip(params["conv"], _CNN_SPEC):
        x = _conv_apply(layer, x, stride)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(mlp_apply(params["neck"], x))
    logits = mlp_apply(params["pi"], x)
    value = mlp_apply(params["vf"], x)[..., 0]
    return logits, value


# -- continuous control (SAC-style) ----------------------------------------

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def gaussian_policy_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Tanh-squashed diagonal Gaussian policy: one torso emitting
    [mean, log_std] (2 * act_dim outputs)."""
    return {"pi": mlp_init(rng, (obs_dim, *hidden, 2 * act_dim))}


def gaussian_policy_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    out = mlp_apply(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mean, log_std


def gaussian_sample(mean, log_std, eps):
    """eps ~ N(0,1). Returns (squashed action in [-1,1], log-prob with the
    tanh change-of-variables correction)."""
    std = jnp.exp(log_std)
    u = mean + std * eps
    a = jnp.tanh(u)
    logp = (-0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi))).sum(-1)
    # d tanh(u)/du = 1 - tanh(u)^2; numerically-stable log form.
    logp -= (2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u))).sum(-1)
    return a, logp


def q_sa_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Twin state-action critics Q(s, a) -> scalar (SAC/TD3 shape)."""
    k1, k2 = jax.random.split(rng)
    return {"q1": mlp_init(k1, (obs_dim + act_dim, *hidden, 1)),
            "q2": mlp_init(k2, (obs_dim + act_dim, *hidden, 1))}


def q_sa_apply(params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x = jnp.concatenate([obs, act], axis=-1)
    return (mlp_apply(params["q1"], x)[..., 0],
            mlp_apply(params["q2"], x)[..., 0])
