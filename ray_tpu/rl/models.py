"""Policy/value networks in JAX (reference: `rllib/models/` catalog).

Small MLP torsos; the TPU story is that the *learner update* is one jit
program (`ray_tpu.rl.learner`) — rollouts stay on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, sizes, dtype=jnp.float32):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (i, o) in zip(keys, zip(sizes[:-1], sizes[1:])):
        params.append({
            "w": jax.random.normal(k, (i, o), dtype) * np.sqrt(2.0 / i),
            "b": jnp.zeros(o, dtype),
        })
    return params


def mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


def actor_critic_init(rng, obs_dim: int, n_actions: int,
                      hidden=(64, 64)) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, (obs_dim, *hidden, n_actions)),
        "vf": mlp_init(k2, (obs_dim, *hidden, 1)),
    }


def actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def q_net_init(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    return {"q": mlp_init(rng, (obs_dim, *hidden, n_actions))}


def q_net_apply(params, obs):
    return mlp_apply(params["q"], obs)


# -- continuous control (SAC-style) ----------------------------------------

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def gaussian_policy_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Tanh-squashed diagonal Gaussian policy: one torso emitting
    [mean, log_std] (2 * act_dim outputs)."""
    return {"pi": mlp_init(rng, (obs_dim, *hidden, 2 * act_dim))}


def gaussian_policy_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    out = mlp_apply(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mean, log_std


def gaussian_sample(mean, log_std, eps):
    """eps ~ N(0,1). Returns (squashed action in [-1,1], log-prob with the
    tanh change-of-variables correction)."""
    std = jnp.exp(log_std)
    u = mean + std * eps
    a = jnp.tanh(u)
    logp = (-0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi))).sum(-1)
    # d tanh(u)/du = 1 - tanh(u)^2; numerically-stable log form.
    logp -= (2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u))).sum(-1)
    return a, logp


def q_sa_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Twin state-action critics Q(s, a) -> scalar (SAC/TD3 shape)."""
    k1, k2 = jax.random.split(rng)
    return {"q1": mlp_init(k1, (obs_dim + act_dim, *hidden, 1)),
            "q2": mlp_init(k2, (obs_dim + act_dim, *hidden, 1))}


def q_sa_apply(params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x = jnp.concatenate([obs, act], axis=-1)
    return (mlp_apply(params["q1"], x)[..., 0],
            mlp_apply(params["q2"], x)[..., 0])
