"""Policy/value networks in JAX (reference: `rllib/models/` catalog).

Small MLP torsos; the TPU story is that the *learner update* is one jit
program (`ray_tpu.rl.learner`) — rollouts stay on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, sizes, dtype=jnp.float32):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (i, o) in zip(keys, zip(sizes[:-1], sizes[1:])):
        params.append({
            "w": jax.random.normal(k, (i, o), dtype) * np.sqrt(2.0 / i),
            "b": jnp.zeros(o, dtype),
        })
    return params


def mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


def actor_critic_init(rng, obs_dim: int, n_actions: int,
                      hidden=(64, 64)) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, (obs_dim, *hidden, n_actions)),
        "vf": mlp_init(k2, (obs_dim, *hidden, 1)),
    }


def actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def q_net_init(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    return {"q": mlp_init(rng, (obs_dim, *hidden, n_actions))}


def q_net_apply(params, obs):
    return mlp_apply(params["q"], obs)


# -- conv torso for pixel observations -------------------------------------
# Reference: `rllib/models/catalog.py` CNN configs (the Atari "nature
# CNN"). NHWC layout + VALID padding so XLA tiles the convs onto the MXU
# without layout shuffles.

_CNN_SPEC = ((32, 8, 4), (64, 4, 2), (64, 3, 1))  # (out_ch, kernel, stride)


def _conv_init(key, k, cin, cout, dtype=jnp.float32):
    fan_in = k * k * cin
    return {
        "w": jax.random.normal(key, (k, k, cin, cout), dtype)
        * np.sqrt(2.0 / fan_in),
        "b": jnp.zeros(cout, dtype),
    }


def _conv_apply(layer, x, stride):
    y = jax.lax.conv_general_dilated(
        x, layer["w"], window_strides=(stride, stride), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return jax.nn.relu(y + layer["b"])


def _cnn_out_dim(hw: int, cnn_spec=_CNN_SPEC) -> int:
    for _, k, s in cnn_spec:
        hw = (hw - k) // s + 1
        assert hw >= 1, "observation too small for the conv stack"
    return hw * hw * cnn_spec[-1][0]


def cnn_actor_critic_init(rng, obs_shape, n_actions: int,
                          hidden: int = 256) -> Dict[str, Any]:
    """Shared conv torso + dense neck, separate pi/vf heads.
    obs_shape = (H, W, C) with H == W."""
    h, w, c = obs_shape
    assert h == w, "square observations only"
    keys = jax.random.split(rng, len(_CNN_SPEC) + 3)
    convs = []
    cin = c
    for key, (cout, k, _) in zip(keys, _CNN_SPEC):
        convs.append(_conv_init(key, k, cin, cout))
        cin = cout
    flat = _cnn_out_dim(h)
    return {
        "conv": convs,
        "neck": mlp_init(keys[-3], (flat, hidden)),
        "pi": mlp_init(keys[-2], (hidden, n_actions)),
        "vf": mlp_init(keys[-1], (hidden, 1)),
    }


def cnn_actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, H, W, C] -> (logits [B, A], value [B]). Integer inputs
    (uint8 frames — shipped that way to quarter the host->HBM traffic)
    rescale to [0, 1] on device; float inputs pass through."""
    x = jnp.asarray(obs)
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(jnp.float32) / 255.0
    for layer, (_, _, stride) in zip(params["conv"], _CNN_SPEC):
        x = _conv_apply(layer, x, stride)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(mlp_apply(params["neck"], x))
    logits = mlp_apply(params["pi"], x)
    value = mlp_apply(params["vf"], x)[..., 0]
    return logits, value


# -- recurrent torsos (R2D2-family) ----------------------------------------
# Reference: `rllib/models/torch/recurrent_net.py` + the R2D2 stack
# (`rllib/algorithms/r2d2/`). A GRU cell scanned over time: the whole
# sequence unroll is one `lax.scan`, so the learner update over [B, T]
# sequences stays a single XLA program (TPU-friendly: the scan body is
# three fused matmuls, no per-step dispatch).


def gru_init(rng, in_dim: int, hidden: int, dtype=jnp.float32):
    k1, k2 = jax.random.split(rng)
    scale_x = np.sqrt(1.0 / in_dim)
    scale_h = np.sqrt(1.0 / hidden)
    return {
        "wx": jax.random.normal(k1, (in_dim, 3 * hidden), dtype) * scale_x,
        "wh": jax.random.normal(k2, (hidden, 3 * hidden), dtype) * scale_h,
        "b": jnp.zeros(3 * hidden, dtype),
    }


def gru_cell(params, h, x):
    """One GRU step: x [B, in], h [B, H] -> h' [B, H]."""
    hid = h.shape[-1]
    gx = x @ params["wx"] + params["b"]
    gh = h @ params["wh"]
    rz_x, n_x = gx[..., :2 * hid], gx[..., 2 * hid:]
    rz_h, n_h = gh[..., :2 * hid], gh[..., 2 * hid:]
    rz = jax.nn.sigmoid(rz_x + rz_h)
    r, z = rz[..., :hid], rz[..., hid:]
    n = jnp.tanh(n_x + r * n_h)
    return (1.0 - z) * n + z * h


def recurrent_q_init(rng, obs_dim: int, n_actions: int,
                     hidden: int = 64, encoder=(64,)):
    """Dense encoder -> GRU -> dueling-free Q head."""
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "enc": mlp_init(k1, (obs_dim, *encoder)),
        "gru": gru_init(k2, encoder[-1], hidden),
        "q": mlp_init(k3, (hidden, n_actions)),
    }


def recurrent_q_step(params, obs, h):
    """One rollout step: obs [B, obs_dim], h [B, H] -> (q [B, A], h')."""
    x = mlp_apply(params["enc"], obs, activate_last=True)
    h = gru_cell(params["gru"], h, x)
    return mlp_apply(params["q"], h), h


def recurrent_q_unroll(params, obs_seq, h0, dones=None,
                       return_hiddens=False):
    """Unroll over time: obs_seq [B, T, obs_dim], h0 [B, H] ->
    (q_seq [B, T, A], h_T). If `dones` [B, T] is given, the CARRIED
    hidden state resets to zero after any done step (episode boundaries
    inside a stored sequence never leak state across episodes). With
    `return_hiddens`, also returns the PRE-reset hidden after each step
    [B, T, H] — what R2D2's bootstrap needs: truncated episodes still
    evaluate Q(next_obs, h) with the un-reset state."""
    def scan_fn(h, inp):
        if dones is None:
            obs_t, done_t = inp, None
        else:
            obs_t, done_t = inp
        q, h_next = recurrent_q_step(params, obs_t, h)
        carry = h_next
        if done_t is not None:
            carry = h_next * (1.0 - done_t.astype(h_next.dtype))[:, None]
        return carry, (q, h_next)

    obs_tm = jnp.swapaxes(obs_seq, 0, 1)  # [T, B, obs]
    xs = obs_tm if dones is None else (obs_tm, jnp.swapaxes(dones, 0, 1))
    h_final, (q_tm, h_tm) = jax.lax.scan(scan_fn, h0, xs)
    q_seq = jnp.swapaxes(q_tm, 0, 1)
    if return_hiddens:
        return q_seq, jnp.swapaxes(h_tm, 0, 1), h_final
    return q_seq, h_final


def recurrent_hidden_size(params) -> int:
    return params["gru"]["wh"].shape[0]


# -- continuous control (SAC-style) ----------------------------------------

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def gaussian_policy_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Tanh-squashed diagonal Gaussian policy: one torso emitting
    [mean, log_std] (2 * act_dim outputs)."""
    return {"pi": mlp_init(rng, (obs_dim, *hidden, 2 * act_dim))}


def gaussian_policy_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    out = mlp_apply(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mean, log_std


def gaussian_sample(mean, log_std, eps):
    """eps ~ N(0,1). Returns (squashed action in [-1,1], log-prob with the
    tanh change-of-variables correction)."""
    std = jnp.exp(log_std)
    u = mean + std * eps
    a = jnp.tanh(u)
    logp = (-0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi))).sum(-1)
    # d tanh(u)/du = 1 - tanh(u)^2; numerically-stable log form.
    logp -= (2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u))).sum(-1)
    return a, logp


def q_sa_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Twin state-action critics Q(s, a) -> scalar (SAC/TD3 shape)."""
    k1, k2 = jax.random.split(rng)
    return {"q1": mlp_init(k1, (obs_dim + act_dim, *hidden, 1)),
            "q2": mlp_init(k2, (obs_dim + act_dim, *hidden, 1))}


def q_sa_apply(params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x = jnp.concatenate([obs, act], axis=-1)
    return (mlp_apply(params["q1"], x)[..., 0],
            mlp_apply(params["q2"], x)[..., 0])
