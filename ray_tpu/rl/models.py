"""Policy/value networks in JAX (reference: `rllib/models/` catalog).

Small MLP torsos; the TPU story is that the *learner update* is one jit
program (`ray_tpu.rl.learner`) — rollouts stay on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, sizes, dtype=jnp.float32):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (i, o) in zip(keys, zip(sizes[:-1], sizes[1:])):
        params.append({
            "w": jax.random.normal(k, (i, o), dtype) * np.sqrt(2.0 / i),
            "b": jnp.zeros(o, dtype),
        })
    return params


def mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


def actor_critic_init(rng, obs_dim: int, n_actions: int,
                      hidden=(64, 64)) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, (obs_dim, *hidden, n_actions)),
        "vf": mlp_init(k2, (obs_dim, *hidden, 1)),
    }


def actor_critic_apply(params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def q_net_init(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    return {"q": mlp_init(rng, (obs_dim, *hidden, n_actions))}


def q_net_apply(params, obs):
    return mlp_apply(params["q"], obs)
