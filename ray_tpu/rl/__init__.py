"""ray_tpu.rl: reinforcement learning (the RLlib-equivalent).

Reference `rllib/` (SURVEY.md §2.4): Algorithm-on-Trainable so Tune
schedules RL runs, CPU rollout-worker actor fleets, jit-compiled learner
updates (the TPU side), V-trace/GAE, replay buffers. Env API is
gymnasium-style with built-in classic-control envs (no gym in the image).
"""

from ray_tpu.rl.algorithm import (  # noqa: F401
    Algorithm,
    AlgorithmConfig,
    WorkerSet,
)
from ray_tpu.rl.algorithms import (  # noqa: F401
    A2C,
    A2CConfig,
    APPO,
    APPOConfig,
    ARS,
    ARSConfig,
    ApexDQN,
    ApexDQNConfig,
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    DQN,
    DQNConfig,
    ES,
    ESConfig,
    IMPALA,
    IMPALAConfig,
    MARWIL,
    MARWILConfig,
    PPO,
    PPOConfig,
    QMIX,
    QMIXConfig,
    R2D2,
    R2D2Config,
    SAC,
    SACConfig,
    TD3,
    TD3Config,
)
from ray_tpu.rl.connectors import (  # noqa: F401
    ClipAction,
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    NormalizeObs,
    UnsquashAction,
)
from ray_tpu.rl.env import (  # noqa: F401
    Box,
    CartPoleEnv,
    Discrete,
    Env,
    MultiAgentEnv,
    PendulumEnv,
    StatelessCartPoleEnv,
    VectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rl.learner import (  # noqa: F401
    Learner,
    LearnerGroup,
    LearnerThread,
)
from ray_tpu.rl.multi_agent import MultiAgentRolloutWorker  # noqa: F401
from ray_tpu.rl.offline import (  # noqa: F401
    InputReader,
    JsonReader,
    JsonWriter,
)
from ray_tpu.rl.exploration import RNDModule  # noqa: F401
from ray_tpu.rl.offline_estimators import (  # noqa: F401
    DirectMethod,
    ImportanceSampling,
    OffPolicyEstimator,
    WeightedImportanceSampling,
)
from ray_tpu.rl.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
    ReservoirReplayBuffer,
    SequenceReplayBuffer,
)
from ray_tpu.rl.rollout_worker import RolloutWorker  # noqa: F401
from ray_tpu.rl.sample_batch import SampleBatch  # noqa: F401
from ray_tpu.rl.catalog import (  # noqa: F401
    ModelConfig,
    ModelSpec,
    get_actor_critic_model,
    get_q_model,
)
from ray_tpu.rl.external import PolicyClient, PolicyServer  # noqa: F401
