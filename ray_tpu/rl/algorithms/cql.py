"""CQL: conservative Q-learning (offline RL).

Reference: `rllib/algorithms/cql/cql.py` + `cql_torch_policy.py`
(Kumar et al. 2020) — SAC machinery trained from a fixed dataset, with
the CQL(H) regularizer added to the critic loss:

    alpha_cql * ( logsumexp_a Q(s, a) - Q(s, a_data) )

where the logsumexp is importance-sampled over uniform actions and
current-policy actions at s and s' (each corrected by its log-density),
pushing Q down on out-of-distribution actions so the learned policy
stays inside the dataset's support. First `bc_iters` actor updates are
plain behavior cloning (the reference's warm-start), then the actor
switches to the SAC objective.

Actions in the dataset are the squashed [-1, 1] actions (the convention
every continuous-control piece of this stack shares); rollout workers
record exactly that column.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.offline import InputReader, JsonReader
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    TERMINATEDS,
)


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(CQL)
        self.input_ = None
        self.cql_alpha = 1.0
        self.num_cql_actions = 10     # sampled actions per logsumexp term
        self.bc_iters = 200           # actor BC warm-start updates
        self.tau = 0.005
        self.initial_alpha = 0.2
        self.target_entropy = "auto"
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.train_batch_size = 256
        self.num_sgd_per_iter = 32
        self.num_rollout_workers = 0

    def offline_data(self, *, input_=None) -> "CQLConfig":
        if input_ is not None:
            self.input_ = input_
        return self


class CQL(Algorithm):
    config_cls = CQLConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(np.prod(env.action_space.shape))
        self._act_dim = act_dim
        k_pi, k_q = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.params = {
            "actor": models.gaussian_policy_init(k_pi, obs_dim, act_dim),
            "critic": models.q_sa_init(k_q, obs_dim, act_dim),
            "log_alpha": jnp.asarray(np.log(cfg.initial_alpha),
                                     jnp.float32),
        }
        self.target_critic = jax.tree.map(jnp.copy, self.params["critic"])
        self.tx = {
            "actor": optax.adam(cfg.actor_lr),
            "critic": optax.adam(cfg.critic_lr),
            "alpha": optax.adam(cfg.alpha_lr),
        }
        self.opt_state = {
            "actor": self.tx["actor"].init(self.params["actor"]),
            "critic": self.tx["critic"].init(self.params["critic"]),
            "alpha": self.tx["alpha"].init(self.params["log_alpha"]),
        }
        inp = cfg.input_
        reader: InputReader = (inp if isinstance(inp, InputReader)
                               else JsonReader(inp))
        # Materialize the dataset once (offline data fits host RAM at
        # these scales; a streaming reader slots in via InputReader).
        data = reader.read_all()
        self._dataset = {
            OBS: np.asarray(data[OBS], np.float32),
            ACTIONS: np.asarray(data[ACTIONS], np.float32),
            REWARDS: np.asarray(data[REWARDS], np.float32),
            TERMINATEDS: np.asarray(
                data[TERMINATEDS] if TERMINATEDS in data
                else data[DONES]).astype(np.float32),
            NEXT_OBS: np.asarray(data[NEXT_OBS], np.float32),
        }
        self._n_rows = len(self._dataset[REWARDS])
        self._rng = np.random.RandomState(cfg.seed)
        self._sgd_steps = 0
        target_entropy = (-float(act_dim)
                          if cfg.target_entropy == "auto"
                          else float(cfg.target_entropy))
        self._update = jax.jit(functools.partial(
            _cql_update, tx=self.tx, gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=target_entropy, cql_alpha=cfg.cql_alpha,
            n_cql=cfg.num_cql_actions), static_argnames=("bc_phase",))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        stats = {}
        for _ in range(cfg.num_sgd_per_iter):
            idx = self._rng.randint(0, self._n_rows,
                                    size=cfg.train_batch_size)
            mb = {k: jnp.asarray(v[idx]) for k, v in
                  self._dataset.items()}
            bc_phase = self._sgd_steps < cfg.bc_iters
            (self.params, self.target_critic, self.opt_state,
             stats) = self._update(
                self.params, self.target_critic, self.opt_state, mb,
                jax.random.PRNGKey(cfg.seed + self._sgd_steps),
                bc_phase=bc_phase)
            self._sgd_steps += 1
        out = {k: float(v) for k, v in stats.items()}
        out["sgd_steps_total"] = self._sgd_steps
        out["dataset_rows"] = self._n_rows
        return out

    def get_weights(self):
        return {"params": self.params, "target": self.target_critic}

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target_critic = jax.tree.map(jnp.asarray, weights["target"])


def _cql_update(params, target_critic, opt_state, mb, rng, *, tx, gamma,
                tau, target_entropy, cql_alpha, n_cql, bc_phase):
    alpha = jnp.exp(params["log_alpha"])
    k1, k2, k3, k4, k5 = jax.random.split(rng, 5)
    b = mb[OBS].shape[0]
    act_dim = mb[ACTIONS].shape[-1]

    # SAC bellman target.
    mean_n, log_std_n = models.gaussian_policy_apply(
        params["actor"], mb[NEXT_OBS])
    a_next, logp_next = models.gaussian_sample(
        mean_n, log_std_n, jax.random.normal(k1, mean_n.shape))
    q1_t, q2_t = models.q_sa_apply(target_critic, mb[NEXT_OBS], a_next)
    q_next = jnp.minimum(q1_t, q2_t) - alpha * logp_next
    target = mb[REWARDS] + gamma * (1.0 - mb[TERMINATEDS]) * q_next
    target = jax.lax.stop_gradient(target)

    def critic_loss_fn(critic):
        q1, q2 = models.q_sa_apply(critic, mb[OBS], mb[ACTIONS])
        bellman = ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

        # CQL(H): importance-sampled logsumexp over uniform + policy
        # actions (at s and s'), each corrected by its log density.
        obs_rep = jnp.repeat(mb[OBS], n_cql, axis=0)

        def q_both(actions_flat):
            qa1, qa2 = models.q_sa_apply(critic, obs_rep, actions_flat)
            return qa1.reshape(b, n_cql), qa2.reshape(b, n_cql)

        a_rand = jax.random.uniform(k2, (b * n_cql, act_dim),
                                    minval=-1.0, maxval=1.0)
        logd_rand = -act_dim * jnp.log(2.0)  # uniform over [-1,1]^d
        mean_c, log_std_c = models.gaussian_policy_apply(
            params["actor"], mb[OBS])
        a_pi, logp_pi = models.gaussian_sample(
            jnp.repeat(mean_c, n_cql, 0), jnp.repeat(log_std_c, n_cql, 0),
            jax.random.normal(k3, (b * n_cql, act_dim)))
        a_pi_n, logp_pi_n = models.gaussian_sample(
            jnp.repeat(mean_n, n_cql, 0), jnp.repeat(log_std_n, n_cql, 0),
            jax.random.normal(k4, (b * n_cql, act_dim)))
        qr = q_both(a_rand)
        qp = q_both(jax.lax.stop_gradient(a_pi))
        qn = q_both(jax.lax.stop_gradient(a_pi_n))
        lp_pi = jax.lax.stop_gradient(logp_pi).reshape(b, n_cql)
        lp_pi_n = jax.lax.stop_gradient(logp_pi_n).reshape(b, n_cql)
        cql_pen = 0.0
        for i, q_data in enumerate((q1, q2)):
            cat = jnp.concatenate(
                [qr[i] - logd_rand, qp[i] - lp_pi, qn[i] - lp_pi_n], 1)
            lse = jax.scipy.special.logsumexp(cat, axis=1) \
                - jnp.log(3 * n_cql)
            cql_pen = cql_pen + (lse - q_data).mean()
        return bellman + cql_alpha * cql_pen, (bellman, cql_pen)

    (c_loss, (bellman, cql_pen)), c_grads = jax.value_and_grad(
        critic_loss_fn, has_aux=True)(params["critic"])
    upd, opt_c = tx["critic"].update(c_grads, opt_state["critic"],
                                     params["critic"])
    params = {**params,
              "critic": optax.apply_updates(params["critic"], upd)}

    # Actor: BC warm-start, then SAC objective on dataset states.
    def actor_loss_fn(actor):
        mean, log_std = models.gaussian_policy_apply(actor, mb[OBS])
        a, logp = models.gaussian_sample(
            mean, log_std, jax.random.normal(k5, mean.shape))
        if bc_phase:
            # log-likelihood of dataset actions under the policy
            u = jnp.arctanh(jnp.clip(mb[ACTIONS], -0.999, 0.999))
            std = jnp.exp(log_std)
            ll = (-0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                          + jnp.log(2 * jnp.pi))).sum(-1)
            return (alpha * logp - ll).mean(), logp
        q1, q2 = models.q_sa_apply(params["critic"], mb[OBS], a)
        return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

    (a_loss, logp), a_grads = jax.value_and_grad(
        actor_loss_fn, has_aux=True)(params["actor"])
    upd, opt_a = tx["actor"].update(a_grads, opt_state["actor"],
                                    params["actor"])
    params = {**params,
              "actor": optax.apply_updates(params["actor"], upd)}

    def alpha_loss_fn(log_alpha):
        return -(jnp.exp(log_alpha)
                 * jax.lax.stop_gradient(logp + target_entropy)).mean()

    al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(
        params["log_alpha"])
    upd, opt_al = tx["alpha"].update(al_grad, opt_state["alpha"],
                                     params["log_alpha"])
    params = {**params,
              "log_alpha": optax.apply_updates(params["log_alpha"], upd)}

    target_critic = jax.tree.map(
        lambda t, o: (1.0 - tau) * t + tau * o,
        target_critic, params["critic"])
    opt_state = {"critic": opt_c, "actor": opt_a, "alpha": opt_al}
    stats = {"critic_loss": c_loss, "bellman_loss": bellman,
             "cql_penalty": cql_pen, "actor_loss": a_loss,
             "alpha": jnp.exp(params["log_alpha"]),
             "bc_phase": jnp.float32(1.0 if bc_phase else 0.0)}
    return params, target_critic, opt_state, stats
