"""Ape-X DQN: distributed prioritized experience replay.

Reference: `rllib/algorithms/apex_dqn/apex_dqn.py` (Horgan et al.) — the
three pieces that distinguish Ape-X from plain DQN:

1. Replay is SHARDED across dedicated replay actors; rollout batches are
   pushed to a shard as they land (actor-side prioritization on insert),
   so buffer memory and sampling throughput scale horizontally.
2. Sampling and learning are fully asynchronous: rollout futures and
   replay-sample futures stay in flight simultaneously; the learner
   consumes whichever sampled minibatch arrives first and ships updated
   priorities back to the owning shard.
3. Per-worker exploration epsilons (worker i explores at a fixed
   eps_i = base ** (1 + i/(n-1) * alpha) instead of a global schedule).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.replay_buffer import (
    PrioritizedReplayBuffer,
    flatten_fragments,
)
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ApexDQN
        self.prioritized_replay = True
        self.num_replay_shards = 2
        self.replay_sample_inflight = 4  # sample futures kept in flight
        # Horgan et al. per-worker epsilon ladder.
        self.worker_eps_base = 0.4
        self.worker_eps_alpha = 7.0


@ray_tpu.remote
class ReplayShard:
    """One shard of the distributed prioritized replay (reference: the
    replay actors `ApexDQN` creates via `ReplayBuffer.as_remote()`)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.buffer = PrioritizedReplayBuffer(capacity, seed=seed)

    def add(self, batch_dict: Dict[str, Any]) -> int:
        self.buffer.add(SampleBatch(batch_dict))
        return len(self.buffer)

    def sample(self, n: int):
        if len(self.buffer) < n:
            return None
        return dict(self.buffer.sample(n))

    def update_priorities(self, idx, prios) -> bool:
        self.buffer.update_priorities(np.asarray(idx), np.asarray(prios))
        return True

    def size(self) -> int:
        return len(self.buffer)


class ApexDQN(DQN):
    config_cls = ApexDQNConfig

    def build_components(self):
        super().build_components()
        cfg = self.algo_config
        self.buffer = None  # replaced by the shard fleet
        self.shards = [
            ReplayShard.remote(
                max(1, cfg.buffer_size // cfg.num_replay_shards),
                seed=cfg.seed + i)
            for i in range(cfg.num_replay_shards)
        ]
        self._next_shard = 0
        self._sample_futs: List = []   # (shard, future)
        self._rollout_futs: List = []  # (worker, future)
        self._worker_eps = [
            cfg.worker_eps_base ** (
                1 + (i / max(1, len(self.workers.workers) - 1))
                * cfg.worker_eps_alpha)
            for i in range(len(self.workers.workers))
        ]

    def _push_rollouts(self):
        """Keep one rollout future in flight per worker at its OWN
        epsilon; landed batches go to replay shards round-robin."""
        steps = 0
        if not self._rollout_futs:
            self._rollout_futs = [
                (w, w.sample.remote(
                    ray_tpu.put((self.params, jnp.float32(eps)))))
                for w, eps in zip(self.workers.workers, self._worker_eps)
            ]
            return 0
        landed, pending = ray_tpu.wait(
            [f for _, f in self._rollout_futs],
            num_returns=len(self._rollout_futs), timeout=0)
        landed_set = {f.binary() if hasattr(f, "binary") else id(f)
                      for f in landed}
        still = []
        for i, (w, f) in enumerate(self._rollout_futs):
            key = f.binary() if hasattr(f, "binary") else id(f)
            if key in landed_set:
                batch = flatten_fragments([ray_tpu.get(f)])
                steps += batch.count
                shard = self.shards[self._next_shard]
                self._next_shard = (self._next_shard + 1) \
                    % len(self.shards)
                shard.add.remote(dict(batch))
                eps = self._worker_eps[
                    self.workers.workers.index(w)]
                still.append((w, w.sample.remote(
                    ray_tpu.put((self.params, jnp.float32(eps))))))
            else:
                still.append((w, f))
        self._rollout_futs = still
        return steps

    def _refill_samples(self):
        cfg = self.algo_config
        while len(self._sample_futs) < cfg.replay_sample_inflight:
            shard = self.shards[np.random.randint(len(self.shards))]
            self._sample_futs.append(
                (shard, shard.sample.remote(cfg.train_batch_size)))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        steps = self._push_rollouts()
        self._refill_samples()
        losses = []
        updates_done = 0
        # Drain up to num_sgd_per_iter sampled minibatches as they land;
        # rollouts, replay sampling and the jitted update all overlap.
        deadline_updates = cfg.num_sgd_per_iter
        while updates_done < deadline_updates and self._sample_futs:
            shard, fut = self._sample_futs.pop(0)
            mb = ray_tpu.get(fut)
            self._refill_samples()
            if mb is None:  # shard still below batch size
                steps += self._push_rollouts()
                sizes = ray_tpu.get(
                    [sh.size.remote() for sh in self.shards])
                if all(s < cfg.train_batch_size for s in sizes):
                    break  # nothing learnable yet anywhere
                continue
            self.params, self.opt_state, loss, td = self._update(
                self.params, self.target_params, self.opt_state,
                {k: jnp.asarray(np.asarray(v)) for k, v in mb.items()
                 if k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)})
            losses.append(float(loss))
            updates_done += 1
            if "batch_indexes" in mb:
                shard.update_priorities.remote(
                    mb["batch_indexes"], np.asarray(td))
        # target_update_freq counts ENV steps, same semantics as the
        # base DQN config field — not learner updates.
        self._steps_since_target += steps
        if self._steps_since_target >= cfg.target_update_freq:
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._steps_since_target = 0
        self._steps_sampled += steps
        sizes = ray_tpu.get([s.size.remote() for s in self.shards])
        return {
            "mean_td_loss": float(np.mean(losses)) if losses else None,
            "learner_updates_this_iter": updates_done,
            "replay_shard_sizes": sizes,
            "buffer_size": int(sum(sizes)),
            "worker_epsilons": [round(e, 4) for e in self._worker_eps],
            "num_env_steps_sampled_this_iter": steps,
        }

    def cleanup(self):
        for s in getattr(self, "shards", []):
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        super().cleanup()
