"""R2D2: recurrent replay distributed DQN.

Reference: `rllib/algorithms/r2d2/r2d2.py` (Kapturowski et al. 2019) —
a GRU Q-network over partially-observable streams, sequence replay with
the *stored-state* strategy plus a burn-in prefix to refresh stale
hidden states, double-Q targets, optional value rescaling
h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x, and per-sequence priorities
p = eta*max|td| + (1-eta)*mean|td|.

TPU shape: the whole update (burn-in unrolls + training-segment unroll +
one batched next-step eval) is a single jit program; the time dimension
is a `lax.scan`, so XLA sees three fused matmuls per step and no Python
in the loop.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.replay_buffer import SequenceReplayBuffer
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    TERMINATEDS,
)


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(R2D2)
        self.hidden_size = 64
        self.encoder = (64,)
        self.burn_in = 8
        self.seq_len = 16
        self.buffer_sequences = 2048
        self.learning_starts = 32        # sequences
        self.train_batch_size = 16       # sequences per SGD step
        self.num_sgd_per_iter = 8
        self.target_update_freq = 1000   # env steps
        self.double_q = True
        self.n_step = 3                  # n-step targets (paper: 5)
        # Feed [one-hot(prev action), prev reward] to the GRU alongside
        # the obs (paper §2.3) — the action history is what lets the net
        # deduce latent state (velocities etc.) in POMDPs.
        self.append_prev_action = True
        self.use_h_transform = False     # value rescaling (Atari-scale)
        self.priority_eta = 0.9
        self.grad_clip = 10.0
        self.huber_delta = 1.0           # Huber TD loss (stability)
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 8000
        self.rollout_fragment_length = 64


class R2D2(Algorithm):
    config_cls = R2D2Config

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self._n_actions = n_actions
        if cfg.append_prev_action:
            obs_dim += n_actions + 1
        self.params = models.recurrent_q_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, n_actions,
            hidden=cfg.hidden_size, encoder=tuple(cfg.encoder))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self.buffer = SequenceReplayBuffer(
            cfg.buffer_sequences, seq_len=cfg.seq_len,
            burn_in=cfg.burn_in, seed=cfg.seed)
        self._steps_sampled = 0
        self._steps_since_target = 0

        # Behaviour policy: epsilon-greedy over the recurrent Q head,
        # expressed as mixture logits so the worker's categorical
        # sampling implements the exploration (same trick as DQN).
        def behaviour(params_and_eps, obs, h):
            params, eps = params_and_eps
            q, h_next = models.recurrent_q_step(params, obs, h)
            n = q.shape[-1]
            probs = (1.0 - eps) * jax.nn.softmax(q * 50.0) + eps / n
            return jnp.log(probs + 1e-9), h_next

        self.workers = WorkerSet(cfg, behaviour, policy_kind="recurrent",
                                 state_size=cfg.hidden_size,
                                 append_prev_action=cfg.append_prev_action)
        self._update = jax.jit(functools.partial(
            _r2d2_update, tx=self.tx, gamma=cfg.gamma,
            burn_in=cfg.burn_in, double_q=cfg.double_q,
            use_h=cfg.use_h_transform, eta=cfg.priority_eta,
            huber_delta=cfg.huber_delta, n_step=cfg.n_step))

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        batches = self.workers.sample((self.params, jnp.float32(eps)))
        count = 0
        for b in batches:
            self.buffer.add(b)
            count += int(np.asarray(b[REWARDS]).size)
        self._steps_sampled += count
        self._steps_since_target += count

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                jb = {k: jnp.asarray(v) for k, v in mb.items()
                      if k != "batch_indexes"}
                self.params, self.opt_state, loss, prio = self._update(
                    self.params, self.target_params, self.opt_state, jb)
                losses.append(float(loss))
                self.buffer.update_priorities(mb["batch_indexes"],
                                              np.asarray(prio))
        if self._steps_since_target >= cfg.target_update_freq:
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._steps_since_target = 0
        return {
            "mean_td_loss": float(np.mean(losses)) if losses else None,
            "epsilon": eps,
            "buffer_sequences": len(self.buffer),
            "num_env_steps_sampled_this_iter": count,
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt_state = self.tx.init(self.params)

    def compute_single_action(self, obs, explore: bool = False,
                              prev_reward: float = 0.0):
        """Greedy recurrent action; maintains hidden state AND the
        prev-action/reward augmentation across calls (call
        `reset_eval_state()` at episode start)."""
        cfg = self.algo_config
        if not hasattr(self, "_eval_hidden") or self._eval_hidden is None:
            self._eval_hidden = jnp.zeros((1, cfg.hidden_size),
                                          jnp.float32)
            self._eval_prev = np.zeros(self._n_actions + 1, np.float32)
        obs_np = np.asarray(obs, np.float32).ravel()
        if cfg.append_prev_action:
            self._eval_prev[-1] = prev_reward
            obs_np = np.concatenate([obs_np, self._eval_prev])
        q, self._eval_hidden = models.recurrent_q_step(
            self.params, jnp.asarray(obs_np)[None], self._eval_hidden)
        a = int(jnp.argmax(q, -1)[0])
        if cfg.append_prev_action:
            self._eval_prev[:] = 0.0
            self._eval_prev[a] = 1.0
        return a

    def reset_eval_state(self):
        self._eval_hidden = None

    def evaluate(self, num_episodes: int = 5,
                 max_steps_per_episode: int = 1000) -> Dict[str, Any]:
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        rewards, lengths = [], []
        for ep in range(num_episodes):
            self.reset_eval_state()
            obs, _ = env.reset(seed=cfg.seed + 10_000 + ep)
            total, steps = 0.0, 0
            r = 0.0
            for _ in range(max_steps_per_episode):
                obs, r, term, trunc, _ = env.step(
                    self.compute_single_action(obs, prev_reward=r))
                total += r
                steps += 1
                if term or trunc:
                    break
            rewards.append(total)
            lengths.append(steps)
        env.close()
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes": num_episodes,
        }}


def _h_transform(x, eps=1e-3):
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def _h_inverse(x, eps=1e-3):
    # Closed-form inverse of the R2D2 value rescaling.
    return jnp.sign(x) * (
        ((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps))
          - 1.0) / (2.0 * eps)) ** 2 - 1.0)


def _unroll(params, obs_seq, dones, h0):
    """GRU-Q unroll with per-step PRE-reset hiddens (see
    models.recurrent_q_unroll — the single scan implementation)."""
    return models.recurrent_q_unroll(params, obs_seq, h0, dones=dones,
                                     return_hiddens=True)


def _r2d2_update(params, target_params, opt_state, mb, *, tx, gamma,
                 burn_in, double_q, use_h, eta, huber_delta, n_step):
    obs, dones = mb[OBS], mb[DONES].astype(jnp.float32)
    h0 = mb["state0"]

    # Burn-in: refresh stale stored state under both nets, no gradients.
    if burn_in > 0:
        ob_b, d_b = obs[:, :burn_in], dones[:, :burn_in]
        _, _, h_on = _unroll(params, ob_b, d_b, h0)
        _, _, h_tg = _unroll(target_params, ob_b, d_b, h0)
        h_on = jax.lax.stop_gradient(h_on)
        h_tg = jax.lax.stop_gradient(h_tg)
    else:
        h_on = h_tg = h0
    sl = slice(burn_in, None)
    ob_t, d_t = obs[:, sl], dones[:, sl]
    acts = mb[ACTIONS][:, sl]
    rews = mb[REWARDS][:, sl]
    terms = mb[TERMINATEDS][:, sl].astype(jnp.float32)
    next_ob = mb[NEXT_OBS][:, sl]
    w_seq = mb["weights"][:, None]
    b, t = acts.shape

    def loss_fn(params):
        q_seq, h_on_seq, _ = _unroll(params, ob_t, d_t, h_on)
        q_taken = jnp.take_along_axis(q_seq, acts[..., None], -1)[..., 0]

        # One batched next-step eval: Q(next_obs_t, h_after_t) under the
        # target net (and online net for double-Q action selection).
        # h_after_t is the PRE-reset hidden (truncated episodes still
        # bootstrap through the true successor obs).
        _, h_tg_seq, _ = _unroll(target_params, ob_t, d_t, h_tg)
        flat_next = next_ob.reshape(b * t, -1)
        q_next_tg, _ = models.recurrent_q_step(
            target_params, flat_next, h_tg_seq.reshape(b * t, -1))
        if double_q:
            q_next_on, _ = models.recurrent_q_step(
                params, flat_next, h_on_seq.reshape(b * t, -1))
            next_a = q_next_on.argmax(-1)
            q_next = jnp.take_along_axis(
                q_next_tg, next_a[:, None], -1)[:, 0]
        else:
            q_next = q_next_tg.max(-1)
        q_next = q_next.reshape(b, t)
        if use_h:
            q_next = _h_inverse(q_next)
        # n-step targets composed along the sequence (uncorrected
        # off-policy n-step, as in the paper): G^1 is the 1-step target;
        # each pass deepens by one step, stopping at episode boundaries
        # and falling back to G^1 at the sequence tail.
        tgt1 = rews + gamma * (1.0 - terms) * q_next
        target = tgt1
        done_mask = d_t > 0.5
        for _ in range(max(0, n_step - 1)):
            shifted = jnp.concatenate(
                [target[:, 1:], target[:, -1:]], axis=1)
            deeper = rews + gamma * shifted
            # Sequence tail has no successor: keep the previous-depth
            # target there (truncated n-step), never self-bootstrap.
            deeper = deeper.at[:, -1].set(target[:, -1])
            target = jnp.where(done_mask, tgt1, deeper)
        if use_h:
            target = _h_transform(target)
        td = q_taken - jax.lax.stop_gradient(target)
        # Huber: quadratic near zero, linear past delta — keeps one
        # high-TD sequence from dominating the gradient.
        abs_td = jnp.abs(td)
        huber = jnp.where(abs_td <= huber_delta, 0.5 * td ** 2,
                          huber_delta * (abs_td - 0.5 * huber_delta))
        loss = (w_seq * huber).mean()
        return loss, td

    (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    abs_td = jnp.abs(td)
    prio = eta * abs_td.max(-1) + (1.0 - eta) * abs_td.mean(-1)
    return params, opt_state, loss, prio
