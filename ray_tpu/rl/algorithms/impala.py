"""IMPALA: async actor-learner with V-trace off-policy correction.

Reference: `rllib/algorithms/impala/` + the learner-thread pattern
(`rllib/execution/learner_thread.py`): rollout workers sample
continuously; a learner thread consumes fragments from a queue, applies
V-trace-corrected updates, and publishes fresh weights. Here the learner
update is one jit program; asynchrony comes from overlapping worker
sampling futures with learner steps.
"""

from __future__ import annotations

import functools
import queue
import threading
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(IMPALA)
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.learner_queue_size = 8
        self.updates_per_iter = 8


def vtrace(behaviour_logp, target_logp, rewards, values, bootstrap,
           dones, gamma, clip_rho, clip_c):
    """All inputs [N, T] (bootstrap [N]); returns (vs, pg_advantages)."""
    rho = jnp.exp(target_logp - behaviour_logp)
    rho_clipped = jnp.minimum(rho, clip_rho)
    c = jnp.minimum(rho, clip_c)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    next_values = jnp.concatenate(
        [values[:, 1:], bootstrap[:, None]], axis=1)
    deltas = rho_clipped * (rewards + discounts * next_values - values)

    def scan_fn(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    # scan right-to-left over time
    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap),
        (deltas.T[::-1], discounts.T[::-1], c.T[::-1]))
    vs_minus_v = vs_minus_v[::-1].T
    vs = values + vs_minus_v
    next_vs = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
    pg_adv = rho_clipped * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALA(Algorithm):
    config_cls = IMPALAConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self.params = models.actor_critic_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, n_actions)
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self.workers = WorkerSet(cfg, models.actor_critic_apply)
        self._update = jax.jit(functools.partial(
            _impala_update, tx=self.tx, gamma=cfg.gamma,
            clip_rho=cfg.vtrace_clip_rho, clip_c=cfg.vtrace_clip_c,
            vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff))
        self._sample_futures = []

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        stats_acc = []
        steps = 0
        # Async pipeline: keep one sample future in flight per worker;
        # learner consumes whichever lands first (learner-thread pattern
        # without the thread — futures give the overlap).
        if not self._sample_futures:
            w_ref = ray_tpu.put(self.params)
            self._sample_futures = [
                (w, w.sample.remote(w_ref)) for w in self.workers.workers]
        for _ in range(cfg.updates_per_iter):
            (worker, fut) = self._sample_futures.pop(0)
            batch = ray_tpu.get(fut)
            # resubmit immediately with current weights (stale by design)
            self._sample_futures.append(
                (worker, worker.sample.remote(ray_tpu.put(self.params))))
            stats = self._do_update(
                {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()})
            stats_acc.append(jax.device_get(stats))
            steps += np.asarray(batch[REWARDS]).size
        agg = {k: float(np.mean([s[k] for s in stats_acc]))
               for k in stats_acc[0]}
        agg["num_env_steps_sampled_this_iter"] = steps
        return agg

    def _do_update(self, batch):
        """One learner update; subclasses (APPO) override to thread
        extra state through `_update` and run post-update bookkeeping."""
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        return stats

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.opt_state = self.tx.init(self.params)

    def cleanup(self):
        self._sample_futures = []
        super().cleanup()


def _impala_update(params, opt_state, batch, *, tx, gamma, clip_rho,
                   clip_c, vf_coeff, entropy_coeff):
    def loss_fn(params):
        n, t = batch[REWARDS].shape
        obs = batch[OBS]
        logits, values = jax.vmap(
            lambda o: models.actor_critic_apply(params, o))(obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
        _, bootstrap = models.actor_critic_apply(
            params, batch[NEXT_OBS][:, -1])
        vs, pg_adv = vtrace(
            batch[LOGPS], target_logp, batch[REWARDS], values,
            bootstrap, batch[DONES], gamma, clip_rho, clip_c)
        pi_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, stats
