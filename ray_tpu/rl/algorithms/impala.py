"""IMPALA: async actor-learner with V-trace off-policy correction.

Reference: `rllib/algorithms/impala/` + the learner-thread pattern
(`rllib/execution/learner_thread.py`): rollout workers sample
continuously; a learner thread consumes fragments from a queue, applies
V-trace-corrected updates, and publishes fresh weights.

TPU shape: the whole update is one jit program owned by a
`ray_tpu.rl.learner.Learner`; with `use_learner_thread=True` that
program runs continuously on-device while rollout futures stream batches
into the queue (true sampling/learning overlap, measured by
`LearnerThread.stats`). `num_learners>0` shards the update across
learner actors (`LearnerGroup`); `num_devices_per_learner>1` shards the
batch across a device mesh inside the program instead (XLA gradient
all-reduce over ICI — the TPU-slice mode). Pixel observations get the
conv torso (`models.cnn_actor_critic_*`) automatically.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.learner import Learner, LearnerGroup, LearnerThread
from ray_tpu.rl.sample_batch import (ACTIONS,
                                     DONES,
                                     LOGPS,
                                     NEXT_OBS,
                                     OBS,
                                     REWARDS)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(IMPALA)
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.learner_queue_size = 8
        self.updates_per_iter = 8
        # new-stack learner scaling (reference LearnerGroupScalingConfig)
        self.use_learner_thread = False
        self.num_learners = 0
        self.num_devices_per_learner = 1
        self.num_sgd_iter = 1
        self.learner_barrier_every = 8

    def learners(self, *, num_learners=None, num_devices_per_learner=None,
                 use_learner_thread=None, num_sgd_iter=None,
                 learner_queue_size=None) -> "IMPALAConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_devices_per_learner is not None:
            self.num_devices_per_learner = num_devices_per_learner
        if use_learner_thread is not None:
            self.use_learner_thread = use_learner_thread
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if learner_queue_size is not None:
            self.learner_queue_size = learner_queue_size
        return self


def vtrace(behaviour_logp, target_logp, rewards, values, bootstrap,
           dones, gamma, clip_rho, clip_c):
    """All inputs [N, T] (bootstrap [N]); returns (vs, pg_advantages)."""
    rho = jnp.exp(target_logp - behaviour_logp)
    rho_clipped = jnp.minimum(rho, clip_rho)
    c = jnp.minimum(rho, clip_c)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    next_values = jnp.concatenate(
        [values[:, 1:], bootstrap[:, None]], axis=1)
    deltas = rho_clipped * (rewards + discounts * next_values - values)

    def scan_fn(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    # scan right-to-left over time
    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap),
        (deltas.T[::-1], discounts.T[::-1], c.T[::-1]))
    vs_minus_v = vs_minus_v[::-1].T
    vs = values + vs_minus_v
    next_vs = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
    pg_adv = rho_clipped * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def _pick_model(env, rng, hidden=(64, 64)):
    """(apply_fn, params): conv torso for [H, W, C] observations, MLP
    otherwise."""
    shape = env.observation_space.shape
    if len(shape) == 3:
        params = models.cnn_actor_critic_init(
            rng, shape, env.action_space.n)
        return models.cnn_actor_critic_apply, params
    obs_dim = int(np.prod(shape))
    params = models.actor_critic_init(rng, obs_dim, env.action_space.n,
                                      hidden)
    return models.actor_critic_apply, params


def impala_loss(params, batch, *, apply_fn, gamma, clip_rho, clip_c,
                vf_coeff, entropy_coeff):
    """V-trace actor-critic loss over [N, T] fragments."""
    logits, values = jax.vmap(
        lambda o: apply_fn(params, o))(batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
    _, bootstrap = apply_fn(params, batch[NEXT_OBS][:, -1])
    vs, pg_adv = vtrace(
        batch[LOGPS], target_logp, batch[REWARDS], values,
        bootstrap, batch[DONES], gamma, clip_rho, clip_c)
    pi_loss = -(target_logp * pg_adv).mean()
    vf_loss = 0.5 * ((values - vs) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


def build_impala_learner(cfg_fields: dict, mesh=None) -> Learner:
    """Picklable learner factory (LearnerGroup actor mode pickles this
    via functools.partial). cfg_fields carries the plain-data subset of
    IMPALAConfig the loss and model need."""
    f = cfg_fields
    env = make_env(f["env_spec"], f["env_config"])
    rng = jax.random.PRNGKey(f["seed"])
    apply_fn, params = _pick_model(env, rng)
    tx = optax.chain(optax.clip_by_global_norm(f["grad_clip"]),
                     optax.adam(f["lr"]))
    loss = functools.partial(
        impala_loss, apply_fn=apply_fn, gamma=f["gamma"],
        clip_rho=f["vtrace_clip_rho"], clip_c=f["vtrace_clip_c"],
        vf_coeff=f["vf_coeff"], entropy_coeff=f["entropy_coeff"])
    return Learner.from_loss(loss, params, tx, mesh=mesh)


def _cfg_fields(cfg: IMPALAConfig) -> dict:
    return {k: getattr(cfg, k) for k in
            ("env_spec", "env_config", "seed", "grad_clip", "lr", "gamma",
             "vtrace_clip_rho", "vtrace_clip_c", "vf_coeff",
             "entropy_coeff")}


class IMPALA(Algorithm):
    config_cls = IMPALAConfig

    def _make_learner_build(self, cfg, mesh):
        """Factory hook subclasses override (APPO swaps in its
        target-net learner) — everything else in build_components is
        shared."""
        return functools.partial(build_impala_learner,
                                 _cfg_fields(cfg), mesh)

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        apply_fn, _ = _pick_model(env, jax.random.PRNGKey(cfg.seed))
        self.apply_fn = apply_fn
        mesh = None
        if cfg.num_devices_per_learner > 1:
            from jax.sharding import Mesh

            devs = jax.devices()[:cfg.num_devices_per_learner]
            mesh = Mesh(np.array(devs), ("data",))
        self.learner_group = LearnerGroup(
            build_learner=self._make_learner_build(cfg, mesh),
            num_learners=cfg.num_learners)
        self.workers = WorkerSet(cfg, apply_fn)
        self.learner_thread = None
        if cfg.use_learner_thread:
            assert self.learner_group.is_local, \
                "learner thread drives the local (mesh) learner"
            self.learner_thread = LearnerThread(
                self.learner_group._learner,
                in_queue_size=cfg.learner_queue_size,
                num_sgd_iter=cfg.num_sgd_iter,
                barrier_every=cfg.learner_barrier_every)
            self.learner_thread.start()
        self._sample_futures = []

    # -- synchronous-ish path (default) ---------------------------------

    def training_step(self) -> Dict[str, Any]:
        if self.learner_thread is not None:
            return self._training_step_async()
        cfg = self.algo_config
        stats_acc = []
        steps = 0
        # Async pipeline: keep one sample future in flight per worker;
        # learner consumes whichever lands first.
        if not self._sample_futures:
            w_ref = ray_tpu.put(self.get_policy_weights())
            self._sample_futures = [
                (w, w.sample.remote(w_ref)) for w in self.workers.workers]
        for _ in range(cfg.updates_per_iter):
            (worker, fut) = self._sample_futures.pop(0)
            batch = ray_tpu.get(fut)
            # resubmit immediately with current weights (stale by design)
            self._sample_futures.append(
                (worker, worker.sample.remote(
                    ray_tpu.put(self.get_policy_weights()))))
            stats = self.learner_group.update(dict(batch))
            stats_acc.append(stats)
            steps += np.asarray(batch[REWARDS]).size
        agg = {k: float(np.mean([s[k] for s in stats_acc]))
               for k in stats_acc[0]}
        agg["num_env_steps_sampled_this_iter"] = steps
        return agg

    # -- learner-thread path --------------------------------------------

    def _training_step_async(self) -> Dict[str, Any]:
        """Feed the learner queue from rollout futures until
        updates_per_iter learner updates have happened; sampling and
        learning overlap the whole time."""
        cfg = self.algo_config
        thread = self.learner_thread
        target = thread.updates + cfg.updates_per_iter
        steps = 0
        if not self._sample_futures:
            w_ref = ray_tpu.put(self.get_policy_weights())
            self._sample_futures = [
                (w, w.sample.remote(w_ref)) for w in self.workers.workers]
        import queue as _q

        while thread.updates < target:
            (worker, fut) = self._sample_futures.pop(0)
            batch = ray_tpu.get(fut)
            self._sample_futures.append(
                (worker, worker.sample.remote(
                    ray_tpu.put(self.get_policy_weights()))))
            steps += np.asarray(batch[REWARDS]).size
            while True:  # bounded put: a dead learner raises, not wedges
                try:
                    thread.put(dict(batch), timeout=5.0)
                    break
                except _q.Full:
                    continue
        agg = dict(thread.stats())
        agg["num_env_steps_sampled_this_iter"] = steps
        return agg

    # -- weights ---------------------------------------------------------

    def get_policy_weights(self):
        """Weights the rollout workers need (params only)."""
        if self.learner_thread is not None:
            return jax.device_get(self.learner_thread.get_weights())
        return jax.device_get(self.learner_group.get_weights())

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, weights):
        # Checkpoint-restore semantics: fresh optimizer moments for the
        # restored params (matches the reference learner state reset).
        self.learner_group.set_weights(
            jax.tree.map(jnp.asarray, weights), reset_optimizer=True)

    def cleanup(self):
        if getattr(self, "learner_thread", None) is not None:
            self.learner_thread.stop()
        if getattr(self, "learner_group", None) is not None:
            self.learner_group.shutdown()
        self._sample_futures = []
        super().cleanup()
