"""QMIX: monotonic value factorization for cooperative multi-agent RL.

Reference: `rllib/algorithms/qmix/qmix.py` + `qmix_policy.py` (Rashid et
al. 2018) — per-agent utility networks (parameter-shared, agent-id
one-hot appended to each obs) whose chosen utilities are combined by a
*monotonic* mixing network: hypernetworks conditioned on the global
state emit the mixer weights, passed through `abs` so dQ_tot/dQ_i >= 0.
That keeps the argmax of Q_tot decomposable into per-agent argmaxes
(the IGM property), so decentralized greedy execution matches the
centralized training target. Double-Q targets against a periodically
synced target copy of both nets; replay over joint transitions.

The global state defaults to the concatenation of all agents' obs (the
reference uses the env-provided state when present; `MultiAgentEnv`
subclasses can expose `get_state()` to do the same here).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.sample_batch import SampleBatch


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(QMIX)
        self.mixing_embed_dim = 32
        self.hypernet_hidden = 64
        self.agent_hidden = (64, 64)
        self.buffer_size = 20_000
        self.learning_starts = 256
        self.train_batch_size = 64
        self.num_sgd_per_iter = 16
        self.target_update_freq = 500   # env steps
        self.double_q = True
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 5000
        self.num_rollout_workers = 1
        self.rollout_fragment_length = 50


def _mixer_init(rng, n_agents: int, state_dim: int, embed: int,
                hyper_hidden: int):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "hyper_w1": models.mlp_init(k1, (state_dim, hyper_hidden,
                                         n_agents * embed)),
        "hyper_b1": models.mlp_init(k2, (state_dim, embed)),
        "hyper_w2": models.mlp_init(k3, (state_dim, hyper_hidden, embed)),
        "hyper_v": models.mlp_init(k4, (state_dim, hyper_hidden, 1)),
    }


def _mixer_apply(params, q_agents, state):
    """q_agents [B, n], state [B, S] -> Q_tot [B]. Monotonic: the
    state-conditioned weights pass through abs()."""
    b, n = q_agents.shape
    w1 = jnp.abs(models.mlp_apply(params["hyper_w1"], state))
    w1 = w1.reshape(b, n, -1)
    b1 = models.mlp_apply(params["hyper_b1"], state)
    hidden = jax.nn.elu(
        jnp.einsum("bn,bne->be", q_agents, w1) + b1)
    w2 = jnp.abs(models.mlp_apply(params["hyper_w2"], state))
    v = models.mlp_apply(params["hyper_v"], state)[:, 0]
    return (hidden * w2).sum(-1) + v


def _agent_q(params, obs_oh):
    """Shared utility net over [B, n, obs+onehot] -> [B, n, A]."""
    b, n, d = obs_oh.shape
    return models.q_net_apply(params, obs_oh.reshape(b * n, d)) \
        .reshape(b, n, -1)


@ray_tpu.remote
class _QMIXWorker:
    """Steps one MultiAgentEnv recording JOINT transitions (all agents'
    obs/actions per step + the global state) — what the mixer trains on,
    unlike the per-policy batches of MultiAgentRolloutWorker."""

    def __init__(self, env_creator, agent_ids: List[str], *,
                 env_config=None, fragment: int = 50, seed: int = 0):
        import jax as _jax

        self.env = env_creator(env_config or {})
        self.agent_ids = agent_ids
        self.fragment = fragment
        self._rng = np.random.RandomState(seed)
        self._apply = _jax.jit(_agent_q)
        self.obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_len = 0
        self._completed: list = []

    def _joint_obs(self, obs_dict) -> np.ndarray:
        n = len(self.agent_ids)
        rows = []
        for i, aid in enumerate(self.agent_ids):
            onehot = np.zeros(n, np.float32)
            onehot[i] = 1.0
            rows.append(np.concatenate([
                np.asarray(obs_dict[aid], np.float32).ravel(), onehot]))
        return np.stack(rows)  # [n, obs+n]

    def _state(self, obs_dict) -> np.ndarray:
        if hasattr(self.env, "get_state"):
            return np.asarray(self.env.get_state(), np.float32)
        return np.concatenate([
            np.asarray(obs_dict[a], np.float32).ravel()
            for a in self.agent_ids])

    def sample(self, params, epsilon: float) -> SampleBatch:
        rows = {"obs": [], "state": [], "actions": [], "rewards": [],
                "dones": [], "terminateds": [], "next_obs": [],
                "next_state": []}
        for _ in range(self.fragment):
            joint = self._joint_obs(self.obs)
            state = self._state(self.obs)
            q = np.asarray(self._apply(params, joint[None]))[0]  # [n, A]
            acts = q.argmax(-1)
            explore = self._rng.rand(len(acts)) < epsilon
            rand = self._rng.randint(0, q.shape[-1], size=len(acts))
            acts = np.where(explore, rand, acts)
            action_dict = {aid: int(a)
                           for aid, a in zip(self.agent_ids, acts)}
            next_obs, rewards, terms, truncs, _ = self.env.step(
                action_dict)
            term = bool(terms.get("__all__", False))
            done = bool(term or truncs.get("__all__", False))
            team_r = float(sum(rewards.values()))
            rows["obs"].append(joint)
            rows["state"].append(state)
            rows["actions"].append(acts.astype(np.int32))
            rows["rewards"].append(team_r)
            rows["dones"].append(done)
            rows["terminateds"].append(term)
            self._episode_reward += team_r
            self._episode_len += 1
            if done:
                self._completed.append(
                    (self._episode_reward, self._episode_len))
                self._episode_reward, self._episode_len = 0.0, 0
                final = next_obs if next_obs else self.obs
                rows["next_obs"].append(self._joint_obs(final))
                rows["next_state"].append(self._state(final))
                self.obs, _ = self.env.reset()
            else:
                rows["next_obs"].append(self._joint_obs(next_obs))
                rows["next_state"].append(self._state(next_obs))
                self.obs = next_obs
        return SampleBatch({k: np.asarray(v) for k, v in rows.items()})

    def episode_stats(self, clear: bool = True):
        stats = list(self._completed)
        if clear:
            self._completed = []
        return stats


class QMIX(Algorithm):
    config_cls = QMIXConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        self.agent_ids = list(env.agent_ids)
        n = len(self.agent_ids)
        obs_dim = int(np.prod(env.observation_space.shape)) + n
        n_actions = env.action_space.n
        state_dim = (len(np.asarray(env.get_state()).ravel())
                     if hasattr(env, "get_state")
                     else (obs_dim - n) * n)
        k1, k2 = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.params = {
            "agent": models.q_net_init(k1, obs_dim, n_actions,
                                       tuple(cfg.agent_hidden)),
            "mixer": _mixer_init(k2, n, state_dim, cfg.mixing_embed_dim,
                                 cfg.hypernet_hidden),
        }
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size)
        self._steps_sampled = 0
        self._steps_since_target = 0
        spec = cfg.env_spec
        creator = spec if callable(spec) and not isinstance(spec, str) \
            else (lambda c, _s=spec: make_env(_s, c))
        self.qworkers = [
            _QMIXWorker.remote(
                creator, self.agent_ids, env_config=cfg.env_config,
                fragment=cfg.rollout_fragment_length,
                seed=cfg.seed + 1000 * (i + 1))
            for i in range(max(1, cfg.num_rollout_workers))
        ]
        self._update = jax.jit(functools.partial(
            _qmix_update, tx=self.tx, gamma=cfg.gamma,
            double_q=cfg.double_q))

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        ref_p = ray_tpu.put(self.params["agent"])
        batches = ray_tpu.get([w.sample.remote(ref_p, eps)
                               for w in self.qworkers])
        count = 0
        for b in batches:
            self.buffer.add(b)
            count += b.count
        self._steps_sampled += count
        self._steps_since_target += count

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.params, self.opt_state, loss = self._update(
                    self.params, self.target_params, self.opt_state,
                    {k: jnp.asarray(v) for k, v in mb.items()})
                losses.append(float(loss))
        if self._steps_since_target >= cfg.target_update_freq:
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._steps_since_target = 0
        return {
            "mean_td_loss": float(np.mean(losses)) if losses else None,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "num_env_steps_sampled_this_iter": count,
        }

    def step(self) -> Dict[str, Any]:
        metrics = self.training_step()
        stats = []
        for s in ray_tpu.get([w.episode_stats.remote()
                              for w in self.qworkers]):
            stats.extend(s)
        for r, _ in stats:
            self._episode_window.append(r)
        self._episode_window = self._episode_window[-100:]
        if self._episode_window:
            metrics["episode_reward_mean"] = float(
                np.mean(self._episode_window))
            metrics["episodes_this_iter"] = len(stats)
        return metrics

    def compute_joint_action(self, obs_dict) -> Dict[str, int]:
        """Decentralized greedy execution: per-agent argmax (IGM)."""
        n = len(self.agent_ids)
        rows = []
        for i, aid in enumerate(self.agent_ids):
            onehot = np.zeros(n, np.float32)
            onehot[i] = 1.0
            rows.append(np.concatenate([
                np.asarray(obs_dict[aid], np.float32).ravel(), onehot]))
        q = np.asarray(_agent_q(self.params["agent"],
                                jnp.asarray(np.stack(rows))[None]))[0]
        return {aid: int(a)
                for aid, a in zip(self.agent_ids, q.argmax(-1))}

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt_state = self.tx.init(self.params)

    def cleanup(self):
        for w in self.qworkers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def _qmix_update(params, target_params, opt_state, mb, *, tx, gamma,
                 double_q):
    def loss_fn(params):
        q_all = _agent_q(params["agent"], mb["obs"])          # [B, n, A]
        acts = mb["actions"].astype(jnp.int32)
        q_taken = jnp.take_along_axis(
            q_all, acts[..., None], -1)[..., 0]               # [B, n]
        q_tot = _mixer_apply(params["mixer"], q_taken, mb["state"])

        q_next_tg = _agent_q(target_params["agent"], mb["next_obs"])
        if double_q:
            q_next_on = _agent_q(params["agent"], mb["next_obs"])
            next_a = q_next_on.argmax(-1)
            q_next = jnp.take_along_axis(
                q_next_tg, next_a[..., None], -1)[..., 0]
        else:
            q_next = q_next_tg.max(-1)
        q_tot_next = _mixer_apply(target_params["mixer"], q_next,
                                  mb["next_state"])
        # Mask the bootstrap on true termination ONLY — time-limit
        # truncations still bootstrap through next_state (the repo-wide
        # TERMINATEDS convention; see sample_batch.py).
        not_term = 1.0 - mb["terminateds"].astype(jnp.float32)
        target = mb["rewards"] + gamma * not_term * q_tot_next
        td = q_tot - jax.lax.stop_gradient(target)
        return (td ** 2).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss
