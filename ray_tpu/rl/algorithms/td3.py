"""TD3: twin-delayed deterministic policy gradient.

Reference: `rllib/algorithms/td3/` (DDPG family) — deterministic actor,
twin Q critics with clipped-double-Q targets, target policy smoothing
(clipped Gaussian noise on the target action), and delayed actor/target
updates. Shares SAC's replay/rollout shape; exploration is Gaussian
noise on the deterministic action (the worker's tanh-Gaussian sampler
with a fixed exploration sigma)."""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.replay_buffer import (ReplayBuffer, flatten_fragments,
                                      sample_stacked)
from ray_tpu.rl.sample_batch import (ACTIONS,
                                     NEXT_OBS,
                                     OBS,
                                     REWARDS,
                                     TERMINATEDS)


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(TD3)
        self.buffer_size = 100_000
        self.learning_starts = 256
        self.train_batch_size = 256
        self.tau = 0.005
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.policy_delay = 2          # actor updates every N critic steps
        self.target_noise = 0.2        # target policy smoothing sigma
        self.target_noise_clip = 0.5
        self.exploration_noise = 0.1   # behaviour-policy sigma
        self.num_sgd_per_iter = 64
        self.num_rollout_workers = 1
        self.rollout_fragment_length = 64


class TD3(Algorithm):
    config_cls = TD3Config

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(np.prod(env.action_space.shape))
        k_pi, k_q = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.params = {
            "actor": models.gaussian_policy_init(k_pi, obs_dim, act_dim),
            "critic": models.q_sa_init(k_q, obs_dim, act_dim),
        }
        self.target = jax.tree.map(jnp.copy, self.params)
        self.tx = {"actor": optax.adam(cfg.actor_lr),
                   "critic": optax.adam(cfg.critic_lr)}
        self.opt_state = {
            "actor": self.tx["actor"].init(self.params["actor"]),
            "critic": self.tx["critic"].init(self.params["critic"]),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size)
        sigma = float(cfg.exploration_noise)

        # Deterministic actor + fixed exploration sigma, expressed in the
        # worker's gaussian sampler (mean=tanh^-1 target, log_std=const).
        def behaviour(actor, obs):
            mean, _ = models.gaussian_policy_apply(actor, obs)
            log_std = jnp.full_like(mean, np.log(max(sigma, 1e-6)))
            return mean, log_std

        self.workers = WorkerSet(cfg, behaviour, policy_kind="gaussian")
        self._update = jax.jit(functools.partial(
            _td3_update_scan, tx=self.tx, gamma=cfg.gamma, tau=cfg.tau,
            policy_delay=cfg.policy_delay,
            target_noise=cfg.target_noise,
            noise_clip=cfg.target_noise_clip))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self.workers.sample(self.params["actor"])
        batch = flatten_fragments(batches)
        self.buffer.add(batch)

        stats = {}
        if len(self.buffer) >= cfg.learning_starts:
            stacked = sample_stacked(
                self.buffer, cfg.num_sgd_per_iter, cfg.train_batch_size,
                (OBS, ACTIONS, REWARDS, TERMINATEDS, NEXT_OBS))
            (self.params, self.target, self.opt_state, stats) = \
                self._update(self.params, self.target, self.opt_state,
                             stacked,
                             jax.random.PRNGKey(
                                 cfg.seed + self.training_iteration))
            stats = {k: float(v) for k, v in stats.items()}
        return {
            **stats,
            "buffer_size": len(self.buffer),
            "num_env_steps_sampled_this_iter": batch.count,
        }

    def get_weights(self):
        return {"params": self.params, "target": self.target}

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target = jax.tree.map(jnp.asarray, weights["target"])


def _td3_update_scan(params, target, opt_state, stacked, rng, *, tx,
                     gamma, tau, policy_delay, target_noise, noise_clip):
    n_steps = stacked[OBS].shape[0]

    def one_step(carry, inp):
        params, target, opt_state, step_i = carry
        mb, step_rng = inp

        # Clipped-double-Q target with target-policy smoothing.
        t_mean, _ = models.gaussian_policy_apply(target["actor"],
                                                 mb[NEXT_OBS])
        noise = jnp.clip(
            target_noise * jax.random.normal(step_rng, t_mean.shape),
            -noise_clip, noise_clip)
        a_next = jnp.clip(jnp.tanh(t_mean) + noise, -1.0, 1.0)
        q1_t, q2_t = models.q_sa_apply(target["critic"], mb[NEXT_OBS],
                                       a_next)
        backup = mb[REWARDS] + gamma * (
            1.0 - mb[TERMINATEDS].astype(jnp.float32)
        ) * jnp.minimum(q1_t, q2_t)
        backup = jax.lax.stop_gradient(backup)

        def critic_loss_fn(critic):
            q1, q2 = models.q_sa_apply(critic, mb[OBS], mb[ACTIONS])
            return ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"])
        upd, opt_c = tx["critic"].update(c_grads, opt_state["critic"],
                                         params["critic"])
        params = {**params,
                  "critic": optax.apply_updates(params["critic"], upd)}

        # Delayed deterministic actor update: maximize Q1(s, pi(s)).
        def actor_loss_fn(actor):
            mean, _ = models.gaussian_policy_apply(actor, mb[OBS])
            q1, _ = models.q_sa_apply(params["critic"], mb[OBS],
                                      jnp.tanh(mean))
            return -q1.mean()

        a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(
            params["actor"])
        do_actor = (step_i % policy_delay) == 0
        upd, opt_a_new = tx["actor"].update(a_grads, opt_state["actor"],
                                            params["actor"])
        new_actor = optax.apply_updates(params["actor"], upd)
        actor = jax.tree.map(
            lambda new, old: jnp.where(do_actor, new, old),
            new_actor, params["actor"])
        # Optimizer state must freeze on skipped steps too: otherwise
        # Adam's moments/step-count absorb gradients from updates that
        # were never applied and the delay degrades to averaging.
        opt_a = jax.tree.map(
            lambda new, old: jnp.where(do_actor, new, old),
            opt_a_new, opt_state["actor"])
        params = {**params, "actor": actor}

        target_new = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o, target, params)
        target = jax.tree.map(
            lambda new, old: jnp.where(do_actor, new, old),
            target_new, target)
        opt_state = {"critic": opt_c, "actor": opt_a}
        stats = {"critic_loss": c_loss, "actor_loss": a_loss}
        return (params, target, opt_state, step_i + 1), stats

    rngs = jax.random.split(rng, n_steps)
    (params, target, opt_state, _), stats = jax.lax.scan(
        one_step, (params, target, opt_state, jnp.int32(0)),
        (stacked, rngs))
    return (params, target, opt_state,
            jax.tree.map(lambda x: x[-1], stats))
