"""APPO: asynchronous PPO.

Reference: `rllib/algorithms/appo/` — IMPALA's async actor-learner
architecture (stale behaviour policies, V-trace off-policy correction)
with PPO's clipped-surrogate policy loss instead of the plain
policy-gradient term, plus a periodically-synced target network used as
the V-trace/value baseline anchor.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
)


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2
        self.target_update_freq = 4  # learner updates between syncs


class APPO(IMPALA):
    config_cls = APPOConfig

    def build_components(self):
        super().build_components()
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._updates_since_sync = 0
        self._update = jax.jit(functools.partial(
            _appo_update, tx=self.tx, gamma=cfg.gamma,
            clip_rho=cfg.vtrace_clip_rho, clip_c=cfg.vtrace_clip_c,
            vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
            clip_param=cfg.clip_param))

    def _do_update(self, batch):
        # IMPALA's async sample pipeline drives this; only the update
        # call (target net threaded through) and the sync cadence differ.
        self.params, self.opt_state, stats = self._update(
            self.params, self.target_params, self.opt_state, batch)
        self._updates_since_sync += 1
        if self._updates_since_sync >= self.algo_config.target_update_freq:
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._updates_since_sync = 0
        return stats

    def get_weights(self):
        return {"params": self.params, "target": self.target_params}

    def set_weights(self, weights):
        if isinstance(weights, dict) and "target" in weights:
            self.params = jax.tree.map(jnp.asarray, weights["params"])
            self.target_params = jax.tree.map(jnp.asarray,
                                              weights["target"])
        else:
            self.params = jax.tree.map(jnp.asarray, weights)
            self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt_state = self.tx.init(self.params)


def _appo_update(params, target_params, opt_state, batch, *, tx, gamma,
                 clip_rho, clip_c, vf_coeff, entropy_coeff, clip_param):
    def loss_fn(params):
        logits, values = jax.vmap(
            lambda o: models.actor_critic_apply(params, o))(batch[OBS])
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
        # V-trace targets/advantages from the (frozen) target network —
        # the reference's stabilized baseline for async updates.
        t_logits, t_values = jax.vmap(
            lambda o: models.actor_critic_apply(target_params, o))(
                batch[OBS])
        t_logp = jnp.take_along_axis(
            jax.nn.log_softmax(t_logits), batch[ACTIONS][..., None],
            axis=-1)[..., 0]
        _, bootstrap = models.actor_critic_apply(
            target_params, batch[NEXT_OBS][:, -1])
        vs, pg_adv = vtrace(
            batch[LOGPS], jax.lax.stop_gradient(t_logp),
            batch[REWARDS], jax.lax.stop_gradient(t_values), bootstrap,
            batch[DONES], gamma, clip_rho, clip_c)
        # PPO clipped surrogate against the BEHAVIOUR logp.
        ratio = jnp.exp(target_logp - batch[LOGPS])
        pg = jnp.minimum(ratio * pg_adv,
                         jnp.clip(ratio, 1 - clip_param,
                                  1 + clip_param) * pg_adv)
        pi_loss = -pg.mean()
        vf_loss = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_ratio": ratio.mean()}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, stats
