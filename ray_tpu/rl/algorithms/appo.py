"""APPO: asynchronous PPO.

Reference: `rllib/algorithms/appo/` — IMPALA's async actor-learner
architecture (stale behaviour policies, V-trace off-policy correction)
with PPO's clipped-surrogate policy loss instead of the plain
policy-gradient term, plus a periodically-synced target network used as
the V-trace/value baseline anchor.

TPU shape: the target network and its sync cadence live INSIDE the
compiled learner step as `extra` state (a device-side counter +
`jnp.where` swap), so the async learner thread never takes a host
round-trip for target syncs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl.algorithms.impala import (
    IMPALA,
    IMPALAConfig,
    _cfg_fields,
    _pick_model,
    vtrace,
)
from ray_tpu.rl.env import make_env
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
)


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2
        self.target_update_freq = 4  # learner updates between syncs


def appo_loss(params, target_params, batch, *, apply_fn, gamma, clip_rho,
              clip_c, vf_coeff, entropy_coeff, clip_param):
    logits, values = jax.vmap(
        lambda o: apply_fn(params, o))(batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
    # V-trace targets/advantages from the (frozen) target network —
    # the reference's stabilized baseline for async updates.
    t_logits, t_values = jax.vmap(
        lambda o: apply_fn(target_params, o))(batch[OBS])
    t_logp = jnp.take_along_axis(
        jax.nn.log_softmax(t_logits), batch[ACTIONS][..., None],
        axis=-1)[..., 0]
    _, bootstrap = apply_fn(target_params, batch[NEXT_OBS][:, -1])
    vs, pg_adv = vtrace(
        batch[LOGPS], jax.lax.stop_gradient(t_logp),
        batch[REWARDS], jax.lax.stop_gradient(t_values), bootstrap,
        batch[DONES], gamma, clip_rho, clip_c)
    # PPO clipped surrogate against the BEHAVIOUR logp.
    ratio = jnp.exp(target_logp - batch[LOGPS])
    pg = jnp.minimum(ratio * pg_adv,
                     jnp.clip(ratio, 1 - clip_param,
                              1 + clip_param) * pg_adv)
    pi_loss = -pg.mean()
    vf_loss = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy, "mean_ratio": ratio.mean(),
                   "loss": total}


def build_appo_learner(cfg_fields: dict, clip_param: float,
                       target_update_freq: int, mesh=None) -> Learner:
    """Learner whose step carries (target_params, update counter) as
    in-program extra state."""
    f = cfg_fields
    env = make_env(f["env_spec"], f["env_config"])
    rng = jax.random.PRNGKey(f["seed"])
    apply_fn, params = _pick_model(env, rng)
    tx = optax.chain(optax.clip_by_global_norm(f["grad_clip"]),
                     optax.adam(f["lr"]))
    loss = functools.partial(
        appo_loss, apply_fn=apply_fn, gamma=f["gamma"],
        clip_rho=f["vtrace_clip_rho"], clip_c=f["vtrace_clip_c"],
        vf_coeff=f["vf_coeff"], entropy_coeff=f["entropy_coeff"],
        clip_param=clip_param)

    def step_fn(state, batch):
        extra = state["extra"]
        (_, stats), grads = jax.value_and_grad(
            lambda p: loss(p, extra["target"], batch),
            has_aux=True)(state["params"])
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        k = extra["k"] + 1
        sync = (k % target_update_freq == 0)
        new_target = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), extra["target"],
            new_params)
        return ({"params": new_params, "opt_state": opt_state,
                 "extra": {"target": new_target, "k": k}}, stats)

    state = {"params": params, "opt_state": tx.init(params),
             "extra": {"target": jax.tree.map(jnp.copy, params),
                       "k": jnp.zeros((), jnp.int32)}}
    return Learner(step_fn, state, mesh=mesh, tx=tx)


class APPO(IMPALA):
    config_cls = APPOConfig

    def _make_learner_build(self, cfg, mesh):
        assert cfg.num_learners == 0, \
            "APPO's stateful target net uses the local (mesh) learner"
        return functools.partial(
            build_appo_learner, _cfg_fields(cfg), cfg.clip_param,
            cfg.target_update_freq, mesh)

    def get_weights(self):
        learner = self.learner_group._learner
        with learner._lock:  # host copies: the step donates its input
            return jax.device_get(
                {"params": learner.state["params"],
                 "target": learner.state["extra"]["target"]})

    def set_weights(self, weights):
        learner = self.learner_group._learner
        if isinstance(weights, dict) and "target" in weights:
            params = jax.tree.map(jnp.asarray, weights["params"])
            target = jax.tree.map(jnp.asarray, weights["target"])
        else:
            params = jax.tree.map(jnp.asarray, weights)
            target = jax.tree.map(jnp.copy, params)
        with learner._lock:
            learner.state = {
                "params": params,
                "opt_state": learner.tx.init(params),
                "extra": {"target": target,
                          "k": jnp.zeros((), jnp.int32)},
            }
