"""PPO: clipped-surrogate policy optimization.

Reference: `rllib/algorithms/ppo/` — GAE advantages, clipped objective,
value-loss + entropy terms, minibatch SGD epochs. The learner update is
one jit program (all epochs+minibatches inside, `lax.scan`-driven) so a
training iteration costs one dispatch — the TPU-idiomatic shape.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    SampleBatch,
    TARGETS,
    VALUES,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 6
        self.sgd_minibatch_size = 128
        self.grad_clip = 0.5


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """rewards/values/dones: [N, T]; last_values: [N]. Returns
    (advantages, targets) each [N, T]. Pure numpy (host-side, tiny)."""
    n, t = rewards.shape
    adv = np.zeros((n, t), np.float32)
    last_gae = np.zeros(n, np.float32)
    next_value = last_values
    for i in range(t - 1, -1, -1):
        nonterminal = 1.0 - dones[:, i].astype(np.float32)
        delta = rewards[:, i] + gamma * next_value * nonterminal \
            - values[:, i]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[:, i] = last_gae
        next_value = values[:, i]
    return adv, adv + values


class PPO(Algorithm):
    config_cls = PPOConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self.params = models.actor_critic_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, n_actions)
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self.workers = WorkerSet(cfg, models.actor_critic_apply)
        self._update = jax.jit(functools.partial(
            _ppo_update, tx=self.tx, clip=cfg.clip_param,
            vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
            num_sgd_iter=cfg.num_sgd_iter,
            minibatch=cfg.sgd_minibatch_size))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self.workers.sample(self.params)
        batch = SampleBatch.concat(batches)  # [N_total, T, ...]
        # Bootstrap values for the final obs of each fragment.
        last_obs = batch["next_obs"][:, -1]
        _, last_values = models.actor_critic_apply(
            self.params, jnp.asarray(last_obs))
        adv, targets = compute_gae(
            np.asarray(batch[REWARDS]), np.asarray(batch[VALUES]),
            np.asarray(batch[DONES]), np.asarray(last_values),
            cfg.gamma, cfg.lambda_)
        flat = {
            OBS: np.asarray(batch[OBS]).reshape(-1,
                                                batch[OBS].shape[-1]),
            ACTIONS: np.asarray(batch[ACTIONS]).ravel(),
            LOGPS: np.asarray(batch[LOGPS]).ravel(),
            ADVANTAGES: adv.ravel(),
            TARGETS: targets.ravel(),
        }
        # Normalize advantages (standard PPO trick).
        a = flat[ADVANTAGES]
        flat[ADVANTAGES] = (a - a.mean()) / (a.std() + 1e-8)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in flat.items()},
            jax.random.PRNGKey(cfg.seed + self.training_iteration))
        return {
            "policy_loss": float(stats["pi_loss"]),
            "vf_loss": float(stats["vf_loss"]),
            "entropy": float(stats["entropy"]),
            "kl": float(stats["kl"]),
            "num_env_steps_sampled_this_iter": int(
                np.asarray(batch[REWARDS]).size),
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.opt_state = self.tx.init(self.params)


def _ppo_loss(params, mb, clip, vf_coeff, entropy_coeff):
    logits, values = models.actor_critic_apply(params, mb[OBS])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, mb[ACTIONS][:, None],
                               axis=1)[:, 0]
    ratio = jnp.exp(logp - mb[LOGPS])
    adv = mb[ADVANTAGES]
    pi_loss = -jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
    vf_loss = 0.5 * ((values - mb[TARGETS]) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    kl = (mb[LOGPS] - logp).mean()
    total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy, "kl": kl}


def _ppo_update(params, opt_state, batch, rng, *, tx, clip, vf_coeff,
                entropy_coeff, num_sgd_iter, minibatch):
    n = batch[OBS].shape[0]
    minibatch = min(minibatch, n)
    n_mb = max(1, n // minibatch)
    usable = n_mb * minibatch

    def epoch(carry, epoch_rng):
        params, opt_state = carry
        perm = jax.random.permutation(epoch_rng, n)[:usable]
        shuffled = jax.tree.map(
            lambda x: x[perm].reshape(n_mb, minibatch, *x.shape[1:]),
            batch)

        def mb_step(carry, mb):
            params, opt_state = carry
            (_, stats), grads = jax.value_and_grad(
                _ppo_loss, has_aux=True)(params, mb, clip, vf_coeff,
                                         entropy_coeff)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), stats

        (params, opt_state), stats = jax.lax.scan(
            mb_step, (params, opt_state), shuffled)
        return (params, opt_state), jax.tree.map(jnp.mean, stats)

    rngs = jax.random.split(rng, num_sgd_iter)
    (params, opt_state), stats = jax.lax.scan(
        epoch, (params, opt_state), rngs)
    return params, opt_state, jax.tree.map(lambda x: x[-1], stats)
