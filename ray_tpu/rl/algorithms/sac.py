"""SAC: soft actor-critic for continuous control.

Reference: `rllib/algorithms/sac/` — tanh-squashed Gaussian policy, twin
Q critics with polyak-averaged targets, entropy-regularized objectives,
automatic temperature (alpha) tuning against a target entropy. The whole
gradient phase of an iteration (n SGD steps over sampled minibatches) is
one jit program driven by `lax.scan` — one dispatch per iteration, the
TPU-idiomatic shape.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.replay_buffer import (ReplayBuffer, flatten_fragments,
                                      sample_stacked)
from ray_tpu.rl.sample_batch import (ACTIONS,
                                     NEXT_OBS,
                                     OBS,
                                     REWARDS,
                                     TERMINATEDS)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self.buffer_size = 100_000
        self.learning_starts = 256
        self.train_batch_size = 256
        self.tau = 0.005            # polyak target-update rate
        self.initial_alpha = 0.2
        self.target_entropy = "auto"  # -act_dim when "auto"
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.num_sgd_per_iter = 64
        self.num_rollout_workers = 1
        self.rollout_fragment_length = 64


class SAC(Algorithm):
    config_cls = SACConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(np.prod(env.action_space.shape))
        k_pi, k_q = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.params = {
            "actor": models.gaussian_policy_init(k_pi, obs_dim, act_dim),
            "critic": models.q_sa_init(k_q, obs_dim, act_dim),
            "log_alpha": jnp.asarray(np.log(cfg.initial_alpha),
                                     jnp.float32),
        }
        self.target_critic = jax.tree.map(jnp.copy, self.params["critic"])
        self.tx = {
            "actor": optax.adam(cfg.actor_lr),
            "critic": optax.adam(cfg.critic_lr),
            "alpha": optax.adam(cfg.alpha_lr),
        }
        self.opt_state = {
            "actor": self.tx["actor"].init(self.params["actor"]),
            "critic": self.tx["critic"].init(self.params["critic"]),
            "alpha": self.tx["alpha"].init(self.params["log_alpha"]),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size)
        target_entropy = (-float(act_dim)
                          if cfg.target_entropy == "auto"
                          else float(cfg.target_entropy))
        self.workers = WorkerSet(
            cfg, lambda p, obs: models.gaussian_policy_apply(p, obs),
            policy_kind="gaussian")
        self._update = jax.jit(functools.partial(
            _sac_update_scan, tx=self.tx, gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=target_entropy))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self.workers.sample(self.params["actor"])
        batch = flatten_fragments(batches)
        self.buffer.add(batch)

        stats = {}
        if len(self.buffer) >= cfg.learning_starts:
            # All minibatches staged up front; the SGD phase is one
            # scan-fused jit dispatch.
            stacked = sample_stacked(
                self.buffer, cfg.num_sgd_per_iter, cfg.train_batch_size,
                (OBS, ACTIONS, REWARDS, TERMINATEDS, NEXT_OBS))
            (self.params, self.target_critic, self.opt_state,
             stats) = self._update(
                self.params, self.target_critic, self.opt_state, stacked,
                jax.random.PRNGKey(cfg.seed + self.training_iteration))
            stats = {k: float(v) for k, v in stats.items()}
        return {
            **stats,
            "buffer_size": len(self.buffer),
            "num_env_steps_sampled_this_iter": batch.count,
        }

    def get_weights(self):
        return {"params": self.params, "target": self.target_critic}

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights["params"])
        self.target_critic = jax.tree.map(jnp.asarray, weights["target"])


def _sac_losses(params, target_critic, mb, rng, *, gamma, target_entropy):
    alpha = jnp.exp(params["log_alpha"])
    k1, k2 = jax.random.split(rng)

    # Critic loss: soft Bellman backup against target twin-min.
    mean_n, log_std_n = models.gaussian_policy_apply(
        params["actor"], mb[NEXT_OBS])
    eps_n = jax.random.normal(k1, mean_n.shape)
    a_next, logp_next = models.gaussian_sample(mean_n, log_std_n, eps_n)
    q1_t, q2_t = models.q_sa_apply(target_critic, mb[NEXT_OBS], a_next)
    q_next = jnp.minimum(q1_t, q2_t) - alpha * logp_next
    # Mask the bootstrap on true termination only: truncated episodes
    # (e.g. Pendulum's time limit) still bootstrap through NEXT_OBS,
    # which the worker records pre-auto-reset.
    target = mb[REWARDS] + gamma * (
        1.0 - mb[TERMINATEDS].astype(jnp.float32)) * q_next
    target = jax.lax.stop_gradient(target)

    def critic_loss_fn(critic):
        q1, q2 = models.q_sa_apply(critic, mb[OBS], mb[ACTIONS])
        return ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

    # Actor loss: maximize twin-min Q of reparameterized action + entropy.
    def actor_loss_fn(actor):
        mean, log_std = models.gaussian_policy_apply(actor, mb[OBS])
        eps = jax.random.normal(k2, mean.shape)
        a, logp = models.gaussian_sample(mean, log_std, eps)
        q1, q2 = models.q_sa_apply(params["critic"], mb[OBS], a)
        q = jnp.minimum(q1, q2)
        return (alpha * logp - q).mean(), logp

    # Alpha loss: drive policy entropy toward the target.
    def alpha_loss_fn(log_alpha, logp):
        return -(jnp.exp(log_alpha)
                 * jax.lax.stop_gradient(logp + target_entropy)).mean()

    return critic_loss_fn, actor_loss_fn, alpha_loss_fn


def _sac_update_scan(params, target_critic, opt_state, stacked, rng, *,
                     tx, gamma, tau, target_entropy):
    n_steps = stacked[OBS].shape[0]

    def one_step(carry, inp):
        params, target_critic, opt_state = carry
        mb, step_rng = inp
        critic_loss_fn, actor_loss_fn, alpha_loss_fn = _sac_losses(
            params, target_critic, mb, step_rng, gamma=gamma,
            target_entropy=target_entropy)

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"])
        upd, opt_c = tx["critic"].update(c_grads, opt_state["critic"],
                                         params["critic"])
        critic = optax.apply_updates(params["critic"], upd)
        params = {**params, "critic": critic}

        (a_loss, logp), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True)(params["actor"])
        upd, opt_a = tx["actor"].update(a_grads, opt_state["actor"],
                                       params["actor"])
        actor = optax.apply_updates(params["actor"], upd)
        params = {**params, "actor": actor}

        al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(
            params["log_alpha"], logp)
        upd, opt_al = tx["alpha"].update(al_grad, opt_state["alpha"],
                                        params["log_alpha"])
        log_alpha = optax.apply_updates(params["log_alpha"], upd)
        params = {**params, "log_alpha": log_alpha}

        target_critic = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            target_critic, params["critic"])
        opt_state = {"critic": opt_c, "actor": opt_a, "alpha": opt_al}
        stats = {"critic_loss": c_loss, "actor_loss": a_loss,
                 "alpha_loss": al_loss, "alpha": jnp.exp(log_alpha),
                 "entropy": -logp.mean()}
        return (params, target_critic, opt_state), stats

    rngs = jax.random.split(rng, n_steps)
    (params, target_critic, opt_state), stats = jax.lax.scan(
        one_step, (params, target_critic, opt_state), (stacked, rngs))
    return (params, target_critic, opt_state,
            jax.tree.map(lambda x: x[-1], stats))
