"""DQN with target network + (prioritized) replay.

Reference: `rllib/algorithms/dqn/` — epsilon-greedy collection into a
replay buffer, TD updates against a periodically-synced target network,
optional double-Q and prioritized replay.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import make_env
from ray_tpu.rl.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
    flatten_fragments,
)
from ray_tpu.rl.sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self.buffer_size = 50_000
        self.learning_starts = 1000
        self.target_update_freq = 500  # env steps
        self.train_batch_size = 32
        self.double_q = True
        self.prioritized_replay = False
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 10_000
        self.num_sgd_per_iter = 32
        # Intrinsic exploration: None or "rnd" (reference
        # `rllib/utils/exploration/` curiosity family).
        self.exploration = None
        self.rnd_coef = 0.5
        self.rnd_embed_dim = 32


class DQN(Algorithm):
    config_cls = DQNConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self.params = models.q_net_init(jax.random.PRNGKey(cfg.seed),
                                        obs_dim, n_actions)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = (PrioritizedReplayBuffer(cfg.buffer_size)
                       if cfg.prioritized_replay
                       else ReplayBuffer(cfg.buffer_size))
        self._steps_sampled = 0
        self._steps_since_target = 0

        # Behaviour policy on workers: epsilon-greedy expressed as logits
        # of the mixture (1-eps)·near-greedy + eps·uniform, so the
        # worker's categorical sampling implements the exploration.
        def behaviour(params_and_eps, obs):
            params, eps = params_and_eps
            q = models.q_net_apply(params, obs)
            n = q.shape[-1]
            greedy_probs = jax.nn.softmax(q * 50.0)
            probs = (1.0 - eps) * greedy_probs + eps / n
            return jnp.log(probs + 1e-9), jnp.zeros(obs.shape[0])

        self.workers = WorkerSet(cfg, behaviour)
        self.rnd = None
        if cfg.exploration == "rnd":
            from ray_tpu.rl.exploration import RNDModule

            self.rnd = RNDModule(obs_dim, embed_dim=cfg.rnd_embed_dim,
                                 seed=cfg.seed)
        elif cfg.exploration is not None:
            raise ValueError(
                f"exploration={cfg.exploration!r}: expected None or "
                "'rnd' (a typo would silently train without the "
                "intrinsic bonus)")
        self._update = jax.jit(functools.partial(
            _dqn_update, tx=self.tx, gamma=cfg.gamma,
            double_q=cfg.double_q))

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        batches = self.workers.sample((self.params, jnp.float32(eps)))
        batch = flatten_fragments(batches)
        mean_bonus = None
        if self.rnd is not None:
            # Novelty bonus mixes into the reward BEFORE replay: the
            # TD targets then value poorly-predicted (novel) states.
            bonus = self.rnd.bonus(np.asarray(batch[OBS]))
            batch[REWARDS] = np.asarray(batch[REWARDS], np.float32) \
                + self.algo_config.rnd_coef * bonus
            mean_bonus = float(bonus.mean())
        self.buffer.add(batch)
        self._steps_sampled += batch.count
        self._steps_since_target += batch.count

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state,
                    {k: jnp.asarray(v) for k, v in mb.items()
                     if k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)})
                losses.append(float(loss))
                if hasattr(self.buffer, "update_priorities") and \
                        "batch_indexes" in mb:
                    self.buffer.update_priorities(
                        mb["batch_indexes"], np.asarray(td))
        if self._steps_since_target >= cfg.target_update_freq:
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._steps_since_target = 0
        out = {
            "mean_td_loss": float(np.mean(losses)) if losses else None,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "num_env_steps_sampled_this_iter": batch.count,
        }
        if mean_bonus is not None:
            out["mean_intrinsic_bonus"] = mean_bonus
        return out

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt_state = self.tx.init(self.params)

    def save_checkpoint(self):
        ckpt = super().save_checkpoint()
        if self.rnd is not None:
            ckpt["rnd"] = self.rnd.state()
        return ckpt

    def load_checkpoint(self, data):
        super().load_checkpoint(data)
        if self.rnd is not None and data.get("rnd"):
            self.rnd.set_state(data["rnd"])


def _dqn_update(params, target_params, opt_state, mb, *, tx, gamma,
                double_q):
    def loss_fn(params):
        q = models.q_net_apply(params, mb[OBS])
        q_taken = jnp.take_along_axis(q, mb[ACTIONS][:, None], 1)[:, 0]
        q_next_target = models.q_net_apply(target_params, mb[NEXT_OBS])
        if double_q:
            q_next_online = models.q_net_apply(params, mb[NEXT_OBS])
            next_a = q_next_online.argmax(-1)
            q_next = jnp.take_along_axis(q_next_target, next_a[:, None],
                                         1)[:, 0]
        else:
            q_next = q_next_target.max(-1)
        target = mb[REWARDS] + gamma * (1.0 - mb[DONES].astype(
            jnp.float32)) * jax.lax.stop_gradient(q_next)
        td = q_taken - target
        return (td ** 2).mean(), td

    (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, jnp.abs(td)
