"""A2C: synchronous advantage actor-critic.

Reference: `rllib/algorithms/a2c/` (sync variant of A3C) — collect one
synchronized batch of fragments from the worker fleet, compute GAE
advantages, take one gradient step on the combined actor-critic loss.
The simplest on-policy algorithm; shares the rollout/GAE machinery with
PPO but no ratio clipping and a single update per batch.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.algorithms.ppo import compute_gae
from ray_tpu.rl.env import make_env
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    OBS,
    REWARDS,
    TARGETS,
    VALUES,
)


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(A2C)
        self.lambda_ = 1.0          # plain n-step returns by default
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 0.5


class A2C(Algorithm):
    config_cls = A2CConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self.params = models.actor_critic_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, n_actions)
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self.workers = WorkerSet(cfg, models.actor_critic_apply)
        self._update = jax.jit(functools.partial(
            _a2c_update, tx=self.tx, vf_coeff=cfg.vf_coeff,
            entropy_coeff=cfg.entropy_coeff))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self.workers.sample(self.params)
        from ray_tpu.rl.sample_batch import SampleBatch

        batch = SampleBatch.concat(batches)  # [N, T, ...]
        last_obs = batch["next_obs"][:, -1]
        _, last_values = models.actor_critic_apply(
            self.params, jnp.asarray(last_obs))
        adv, targets = compute_gae(
            np.asarray(batch[REWARDS]), np.asarray(batch[VALUES]),
            np.asarray(batch[DONES]), np.asarray(last_values),
            cfg.gamma, cfg.lambda_)
        flat = {
            OBS: np.asarray(batch[OBS]).reshape(-1, batch[OBS].shape[-1]),
            ACTIONS: np.asarray(batch[ACTIONS]).ravel(),
            ADVANTAGES: adv.ravel(),
            TARGETS: targets.ravel(),
        }
        a = flat[ADVANTAGES]
        flat[ADVANTAGES] = (a - a.mean()) / (a.std() + 1e-8)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in flat.items()})
        return {
            "policy_loss": float(stats["pi_loss"]),
            "vf_loss": float(stats["vf_loss"]),
            "entropy": float(stats["entropy"]),
            "num_env_steps_sampled_this_iter": int(
                np.asarray(batch[REWARDS]).size),
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.opt_state = self.tx.init(self.params)


def _a2c_update(params, opt_state, batch, *, tx, vf_coeff, entropy_coeff):
    def loss_fn(params):
        logits, values = models.actor_critic_apply(params, batch[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch[ACTIONS][:, None],
                                   axis=1)[:, 0]
        pi_loss = -(logp * batch[ADVANTAGES]).mean()
        vf_loss = 0.5 * ((values - batch[TARGETS]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, stats
