"""BC and MARWIL: offline / imitation learning.

Reference: `rllib/algorithms/bc/` and `rllib/algorithms/marwil/` — BC is
plain behavioral cloning (maximize log-likelihood of dataset actions);
MARWIL weights the cloning term by exponentiated advantages
(`exp(beta * A / c)`) estimated with a learned value function, so better
trajectories are imitated harder. BC is exactly MARWIL with beta=0 (the
reference implements it that way too).

Data comes from an `InputReader` (JSONL files recorded by `JsonWriter`,
or any SampleBatch source) instead of live rollout workers.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.offline import InputReader, JsonReader
from ray_tpu.rl.sample_batch import (
    ACTIONS,
    DONES,
    OBS,
    REWARDS,
    SampleBatch,
)


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MARWIL)
        self.beta = 1.0             # advantage-weighting temperature
        self.vf_coeff = 1.0
        self.grad_clip = 0.5
        self.input_ = None          # path / list of paths / InputReader
        self.train_batch_size = 512
        self.num_rollout_workers = 0

    def offline_data(self, *, input_=None) -> "MARWILConfig":
        if input_ is not None:
            self.input_ = input_
        return self


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BC
        self.beta = 0.0


class MARWIL(Algorithm):
    config_cls = MARWILConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = env.action_space.n
        self.params = models.actor_critic_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, n_actions)
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        inp = cfg.input_
        self.reader: InputReader = (inp if isinstance(inp, InputReader)
                                    else JsonReader(inp))
        # MA advantage normalizer (running mean of squared advantages,
        # the reference's `c^2` estimate).
        self._c2 = 1.0
        self._update = jax.jit(functools.partial(
            _marwil_update, tx=self.tx, beta=cfg.beta,
            vf_coeff=cfg.vf_coeff))

    def _reward_to_go(self, rew: np.ndarray, done: np.ndarray):
        """Discounted reward-to-go within ONE time-ordered trajectory
        array [T] (resets at dones). Must NOT be applied across
        env/fragment joins — callers compute it per fragment."""
        returns = np.zeros_like(rew)
        acc = 0.0
        for i in range(len(rew) - 1, -1, -1):
            acc = rew[i] + self.algo_config.gamma * acc * (1.0 - done[i])
            returns[i] = acc
        return returns

    def _next_train_batch(self) -> SampleBatch:
        cfg = self.algo_config
        rows, count = [], 0
        while count < cfg.train_batch_size:
            b = self.reader.next()
            rew = np.asarray(b[REWARDS], np.float32)
            done = (np.asarray(b[DONES]).astype(np.float32)
                    if DONES in b else np.zeros_like(rew))
            # Returns are computed per fragment per env row BEFORE any
            # flatten/concat: a single backward pass over joined rows
            # would leak one trajectory's rewards into another's.
            if rew.ndim == 2:  # [N, T] rollout fragments
                returns = np.stack([
                    self._reward_to_go(rew[i], done[i])
                    for i in range(rew.shape[0])])
            else:
                returns = self._reward_to_go(rew, done)
            b = SampleBatch({**b, "returns": returns})
            # Flatten [N, T, ...] fragments to [N*T, ...] rows.
            if np.asarray(b[OBS]).ndim == 3:
                b = SampleBatch({
                    k: np.asarray(v).reshape(
                        -1, *np.asarray(v).shape[2:])
                    for k, v in b.items()})
            rows.append(b)
            count += b.count
        return SampleBatch.concat(rows)

    def training_step(self) -> Dict[str, Any]:
        batch = self._next_train_batch()
        data = {
            OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
            ACTIONS: jnp.asarray(np.asarray(batch[ACTIONS]).astype(
                np.int32)),
            "returns": jnp.asarray(np.asarray(batch["returns"],
                                              np.float32)),
        }
        self.params, self.opt_state, stats, c2 = self._update(
            self.params, self.opt_state, data, jnp.float32(self._c2))
        self._c2 = float(c2)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
        self.opt_state = self.tx.init(self.params)


class BC(MARWIL):
    config_cls = BCConfig


def _marwil_update(params, opt_state, data, c2, *, tx, beta, vf_coeff):
    def loss_fn(params):
        logits, values = models.actor_critic_apply(params, data[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, data[ACTIONS][:, None],
                                   axis=1)[:, 0]
        adv = data["returns"] - values
        if beta > 0.0:
            w = jnp.exp(beta * jax.lax.stop_gradient(
                adv / jnp.sqrt(c2 + 1e-8)))
            w = jnp.minimum(w, 20.0)  # explosion guard (reference cap)
        else:
            w = jnp.ones_like(logp)
        pi_loss = -(w * logp).mean()
        vf_loss = (adv ** 2).mean()
        total = pi_loss + (vf_coeff * vf_loss if beta > 0.0 else 0.0)
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "mean_weight": w.mean(),
                       "adv2": jax.lax.stop_gradient((adv ** 2).mean())}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    # Polyak-update the advantage scale estimate.
    c2 = 0.99 * c2 + 0.01 * stats.pop("adv2")
    return params, opt_state, stats, c2
