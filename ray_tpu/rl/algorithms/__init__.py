from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rl.algorithms.a2c import A2C, A2CConfig  # noqa: F401
from ray_tpu.rl.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.algorithms.bc import (  # noqa: F401
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rl.algorithms.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rl.algorithms.apex_dqn import (  # noqa: F401
    ApexDQN,
    ApexDQNConfig,
)
from ray_tpu.rl.algorithms.r2d2 import R2D2, R2D2Config  # noqa: F401
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rl.algorithms.qmix import QMIX, QMIXConfig  # noqa: F401
from ray_tpu.rl.algorithms.es import (  # noqa: F401
    ARS,
    ARSConfig,
    ES,
    ESConfig,
)
