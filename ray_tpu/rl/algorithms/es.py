"""ES + ARS: gradient-free policy search over a worker fleet.

Reference: `rllib/algorithms/es/es.py` (Salimans et al. 2017) and
`rllib/algorithms/ars/ars.py` (Mania et al. 2018). The design keeps the
reference's key scaling trick: a big **shared noise table** placed in
the object store ONCE (`ray_tpu.put`), with workers indexing slices by
integer offset — broadcast cost is one object, not pop_size × dim
gaussians per generation (reference `SharedNoiseTable`,
`rllib/algorithms/es/utils.py`).

Each generation: antithetic pairs theta ± sigma*eps_i are evaluated by
the fleet, returns are rank-normalized (ES) or top-k selected and
std-scaled (ARS), and the weighted noise sum becomes the update. Pure
numpy on the workers — a linear/MLP policy forward at these sizes is
faster than any device round-trip, and the TPU stays free for learners
that need it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, make_env


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ES)
        self.pop_size = 16            # perturbation PAIRS per generation
        self.noise_std = 0.1
        self.step_size = 0.05         # SGD step on the estimated gradient
        self.l2_coeff = 0.005
        self.noise_table_size = 4_000_000
        self.episodes_per_eval = 1
        self.max_episode_steps = 500
        self.hidden: Tuple[int, ...] = (32,)
        self.theta_init = "normal"    # "zeros" for ARS-style linear
        self.num_rollout_workers = 4


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ARS
        self.top_frac = 0.5           # fraction of directions kept
        self.hidden = ()              # ARS paper: linear policies...
        self.theta_init = "zeros"     # ...initialized at zero (§3)


def _mlp_sizes(obs_dim: int, out_dim: int, hidden) -> List[int]:
    return [obs_dim, *hidden, out_dim]


def _theta_dim(sizes) -> int:
    return sum(i * o + o for i, o in zip(sizes[:-1], sizes[1:]))


def _forward(theta: np.ndarray, sizes, obs: np.ndarray) -> np.ndarray:
    """Pure-numpy MLP forward (tanh hidden, linear output)."""
    x = obs
    off = 0
    for li, (i, o) in enumerate(zip(sizes[:-1], sizes[1:])):
        w = theta[off:off + i * o].reshape(i, o)
        off += i * o
        b = theta[off:off + o]
        off += o
        x = x @ w + b
        if li < len(sizes) - 2:
            x = np.tanh(x)
    return x


@ray_tpu.remote
class _ESWorker:
    def __init__(self, env_spec, env_config, sizes, noise: np.ndarray,
                 seed: int, max_steps: int, episodes: int):
        self.env = make_env(env_spec, env_config)
        self.sizes = list(sizes)
        self.noise = np.asarray(noise)
        self.dim = _theta_dim(self.sizes)
        self.max_steps = max_steps
        self.episodes = episodes
        self.continuous = isinstance(self.env.action_space, Box)
        self._seed = seed
        self._ep = 0
        # Welford accumulators for the generation's observations — the
        # ARS paper's running obs normalization (v2), merged head-side.
        self._obs_n = 0
        self._obs_sum = np.zeros(self.sizes[0], np.float64)
        self._obs_sq = np.zeros(self.sizes[0], np.float64)

    def _rollout(self, theta, mean, std) -> Tuple[float, int]:
        total, steps = 0.0, 0
        for _ in range(self.episodes):
            self._ep += 1
            obs, _ = self.env.reset(seed=self._seed + self._ep)
            for _ in range(self.max_steps):
                o = np.asarray(obs, np.float32).ravel()
                self._obs_n += 1
                self._obs_sum += o
                self._obs_sq += o * o
                out = _forward(theta, self.sizes, (o - mean) / std)
                if self.continuous:
                    low = self.env.action_space.low
                    high = self.env.action_space.high
                    a = low + (np.tanh(out) + 1.0) * 0.5 * (high - low)
                else:
                    a = int(out.argmax())
                obs, r, term, trunc, _ = self.env.step(a)
                total += r
                steps += 1
                if term or trunc:
                    break
        return total / self.episodes, steps

    def evaluate(self, theta: np.ndarray, indices: List[int],
                 sigma: float, mean: np.ndarray,
                 std: np.ndarray) -> Dict[str, Any]:
        """Antithetic evaluation of theta ± sigma*noise[idx:idx+dim]
        for each index, under the broadcast obs normalization. Returns
        per-pair (r_pos, r_neg), step count, and the worker's obs-stat
        accumulators for the head-side merge."""
        r_pos, r_neg, steps = [], [], 0
        for idx in indices:
            eps = self.noise[idx:idx + self.dim]
            rp, sp = self._rollout(theta + sigma * eps, mean, std)
            rn, sn = self._rollout(theta - sigma * eps, mean, std)
            r_pos.append(rp)
            r_neg.append(rn)
            steps += sp + sn
        stats = (self._obs_n, self._obs_sum.copy(), self._obs_sq.copy())
        return {"r_pos": r_pos, "r_neg": r_neg, "steps": steps,
                "obs_stats": stats}


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Reference `compute_centered_ranks`: ranks scaled to [-0.5, 0.5]."""
    ranks = np.empty(x.size, dtype=np.float64)
    ranks[x.ravel().argsort()] = np.arange(x.size)
    return (ranks / (x.size - 1) - 0.5).reshape(x.shape)


class ES(Algorithm):
    config_cls = ESConfig

    def build_components(self):
        cfg = self.algo_config
        env = make_env(cfg.env_spec, cfg.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        out_dim = (int(np.prod(env.action_space.shape))
                   if isinstance(env.action_space, Box)
                   else env.action_space.n)
        self.sizes = _mlp_sizes(obs_dim, out_dim, tuple(cfg.hidden))
        self.dim = _theta_dim(self.sizes)
        self._action_space = env.action_space
        rng = np.random.RandomState(cfg.seed)
        self.theta = (np.zeros(self.dim, np.float32)
                      if cfg.theta_init == "zeros" else
                      (rng.randn(self.dim) / np.sqrt(obs_dim))
                      .astype(np.float32))
        # Shared noise table: one object-store put, every worker maps it.
        noise = rng.randn(cfg.noise_table_size).astype(np.float32)
        self._noise = noise
        noise_ref = ray_tpu.put(noise)
        self._rng = rng
        self.esworkers = [
            _ESWorker.remote(cfg.env_spec, cfg.env_config, self.sizes,
                             noise_ref, cfg.seed + 7919 * (i + 1),
                             cfg.max_episode_steps, cfg.episodes_per_eval)
            for i in range(max(1, cfg.num_rollout_workers))
        ]
        self._gen = 0
        obs_dim = self.sizes[0]
        self._obs_n = 0
        self._obs_sum = np.zeros(obs_dim, np.float64)
        self._obs_sq = np.zeros(obs_dim, np.float64)

    def _obs_norm(self):
        if self._obs_n < 2:
            return (np.zeros(self.sizes[0], np.float32),
                    np.ones(self.sizes[0], np.float32))
        mean = self._obs_sum / self._obs_n
        var = np.maximum(self._obs_sq / self._obs_n - mean ** 2, 1e-8)
        return mean.astype(np.float32), np.sqrt(var).astype(np.float32)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        idx_max = cfg.noise_table_size - self.dim
        indices = self._rng.randint(0, idx_max, size=cfg.pop_size)
        shards = np.array_split(indices, len(self.esworkers))
        theta_ref = ray_tpu.put(self.theta)
        mean, std = self._obs_norm()
        outs = ray_tpu.get([
            w.evaluate.remote(theta_ref, [int(i) for i in shard],
                              cfg.noise_std, mean, std)
            for w, shard in zip(self.esworkers, shards) if len(shard)])
        # Merge worker obs stats (workers send cumulative accumulators;
        # take the max-n copy per worker slot by just re-summing — each
        # worker's tuple is its lifetime total, so rebuild the global).
        self._obs_n = sum(o["obs_stats"][0] for o in outs)
        self._obs_sum = sum(o["obs_stats"][1] for o in outs)
        self._obs_sq = sum(o["obs_stats"][2] for o in outs)
        r_pos = np.array(sum((o["r_pos"] for o in outs), []))
        r_neg = np.array(sum((o["r_neg"] for o in outs), []))
        used = [i for shard in shards for i in shard][:len(r_pos)]
        steps = sum(o["steps"] for o in outs)
        self._apply_update(np.asarray(used), r_pos, r_neg)
        self._gen += 1
        return {
            "episode_reward_mean": float(
                np.concatenate([r_pos, r_neg]).mean()),
            "episode_reward_max": float(max(r_pos.max(), r_neg.max())),
            "generation": self._gen,
            "num_env_steps_sampled_this_iter": int(steps),
            "theta_norm": float(np.linalg.norm(self.theta)),
        }

    def _apply_update(self, indices, r_pos, r_neg):
        cfg = self.algo_config
        ranks = _centered_ranks(np.stack([r_pos, r_neg]))
        weights = ranks[0] - ranks[1]                  # [pairs]
        grad = np.zeros(self.dim, np.float64)
        for w, idx in zip(weights, indices):
            grad += w * self._noise[idx:idx + self.dim]
        grad /= (len(indices) * cfg.noise_std)
        self.theta = (self.theta
                      + cfg.step_size * grad.astype(np.float32)
                      - cfg.step_size * cfg.l2_coeff * self.theta)

    def compute_single_action(self, obs, explore: bool = False):
        mean, std = self._obs_norm()
        out = _forward(self.theta, self.sizes,
                       (np.asarray(obs, np.float32).ravel() - mean) / std)
        space = self._action_space
        if isinstance(space, Box):
            low, high = space.low, space.high
            return low + (np.tanh(out) + 1.0) * 0.5 * (high - low)
        return int(out.argmax())

    def get_weights(self):
        return {"theta": self.theta, "sizes": self.sizes,
                "obs_stats": (self._obs_n, self._obs_sum, self._obs_sq)}

    def set_weights(self, weights):
        self.theta = np.asarray(weights["theta"], np.float32)
        self.sizes = list(weights["sizes"])
        if "obs_stats" in weights:
            (self._obs_n, self._obs_sum,
             self._obs_sq) = weights["obs_stats"]

    def cleanup(self):
        for w in getattr(self, "esworkers", []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


class ARS(ES):
    """Augmented random search: keep only the top-k directions by
    max(r+, r-) and scale by the std of the surviving returns
    (reference `rllib/algorithms/ars/ars.py`)."""

    config_cls = ARSConfig

    def _apply_update(self, indices, r_pos, r_neg):
        cfg = self.algo_config
        k = max(1, int(len(indices) * cfg.top_frac))
        score = np.maximum(r_pos, r_neg)
        top = np.argsort(-score)[:k]
        r_std = np.concatenate([r_pos[top], r_neg[top]]).std() + 1e-8
        grad = np.zeros(self.dim, np.float64)
        for i in top:
            grad += (r_pos[i] - r_neg[i]) * \
                self._noise[indices[i]:indices[i] + self.dim]
        grad /= (k * r_std)
        self.theta = (self.theta
                      + cfg.step_size * grad.astype(np.float32)
                      - cfg.step_size * cfg.l2_coeff * self.theta)
