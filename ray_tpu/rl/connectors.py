"""Connectors: composable obs/action transformation pipelines.

Reference: `rllib/connectors/` — small stateless-or-stateful transforms
chained between env and policy (agent/obs connectors) and between policy
and env (action connectors). Configure via
`AlgorithmConfig.rollouts(obs_connectors=..., action_connectors=...)`;
each RolloutWorker gets its own (pickled) copy. Stateful connector state
(e.g. NormalizeObs running stats) is worker-local during training;
`Algorithm.save_checkpoint` captures worker 0's state and restore pushes
it to every worker, so evaluation sees the training-time preprocessing.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class Connector:
    """One transform. `__call__` maps a batched array to a batched array."""

    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Sequence[Connector] = ()):
        self.connectors: List[Connector] = list(connectors)

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c)
        return self

    def __call__(self, x: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            x = c(x)
        return x

    def get_state(self) -> Dict[str, Any]:
        return {str(i): c.get_state()
                for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])


# -- obs connectors ---------------------------------------------------------


class FlattenObs(Connector):
    """[B, ...] → [B, prod(...)] (reference flatten preprocessor)."""

    def __call__(self, x):
        x = np.asarray(x)
        return x.reshape(x.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, x):
        return np.clip(x, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std normalization (reference MeanStdFilter). State
    (count/mean/m2) rides along with policy weights via get/set_state."""

    def __init__(self, epsilon: float = 1e-8, clip: Optional[float] = 10.0,
                 update: bool = True):
        self.eps = epsilon
        self.clip = clip
        self.update = update
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, x):
        x = np.asarray(x, np.float64)
        if self.mean is None:
            self.mean = np.zeros(x.shape[1:], np.float64)
            self.m2 = np.zeros(x.shape[1:], np.float64)
        if self.update:
            # Chan parallel-update of count/mean/M2 with the batch stats.
            bc = float(len(x))
            bmean = x.mean(0)
            bm2 = ((x - bmean) ** 2).sum(0)
            delta = bmean - self.mean
            tot = self.count + bc
            self.mean = self.mean + delta * bc / max(tot, 1.0)
            self.m2 = self.m2 + bm2 + delta ** 2 * self.count * bc \
                / max(tot, 1.0)
            self.count = tot
        var = self.m2 / max(self.count - 1.0, 1.0)
        out = (x - self.mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def get_state(self):
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


# -- action connectors ------------------------------------------------------


class ClipAction(Connector):
    def __init__(self, low, high):
        self.low, self.high = np.asarray(low), np.asarray(high)

    def __call__(self, a):
        return np.clip(a, self.low, self.high)


class UnsquashAction(Connector):
    """[-1, 1] → [low, high] (reference `unsquash_action`)."""

    def __init__(self, low, high):
        self.low, self.high = np.asarray(low), np.asarray(high)

    def __call__(self, a):
        return self.low + (np.asarray(a) + 1.0) * 0.5 \
            * (self.high - self.low)
