"""Exploration modules: intrinsic-motivation bonuses.

Reference: `rllib/utils/exploration/curiosity.py` (ICM) and
`random_encoder.py` (RND/RE3). Implemented here as Random Network
Distillation (Burda et al. 2019) — the simplest curiosity signal that
needs no inverse/forward dynamics model:

- a FIXED random target network embeds observations;
- a trained predictor regresses the target embedding;
- the per-observation prediction error IS the novelty bonus (novel
  states are poorly predicted), normalized by a running std so the
  bonus scale is stationary.

`RNDModule.bonus(obs)` returns intrinsic rewards and updates the
predictor — algorithms mix `reward + coef * bonus` before their buffer
add (see DQNConfig.exploration="rnd"). The whole predictor update is
one jitted step (TPU-friendly: two small matmul stacks)."""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import models


def _rnd_update(pred_params, opt_state, obs, target_params, *, tx):
    def loss_fn(p):
        tgt = models.mlp_apply(target_params, obs)
        out = models.mlp_apply(p, obs)
        per_obs = ((out - jax.lax.stop_gradient(tgt)) ** 2).mean(-1)
        return per_obs.mean(), per_obs

    (_, per_obs), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(pred_params)
    updates, opt_state = tx.update(grads, opt_state, pred_params)
    pred_params = optax.apply_updates(pred_params, updates)
    return pred_params, opt_state, per_obs


class RNDModule:
    """Random Network Distillation novelty bonus."""

    def __init__(self, obs_dim: int, *, embed_dim: int = 32,
                 hidden: Tuple[int, ...] = (64,), lr: float = 1e-3,
                 seed: int = 0):
        k_t, k_p = jax.random.split(jax.random.PRNGKey(seed))
        sizes = (obs_dim, *hidden, embed_dim)
        self.target = models.mlp_init(k_t, sizes)  # frozen
        self.pred = models.mlp_init(k_p, sizes)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.pred)
        self._update = jax.jit(functools.partial(
            _rnd_update, tx=self.tx))
        # Running bonus normalization (Welford) so the intrinsic scale
        # stays comparable to env rewards as the predictor improves.
        self._count = 1e-4
        self._mean = 0.0
        self._m2 = 0.0

    def bonus(self, obs: np.ndarray) -> np.ndarray:
        """Intrinsic rewards for a batch of observations; trains the
        predictor on the same batch (the RND schedule)."""
        obs_j = jnp.asarray(np.asarray(obs, np.float32).reshape(
            len(obs), -1))
        self.pred, self.opt_state, per_obs = self._update(
            self.pred, self.opt_state, obs_j, self.target)
        err = np.asarray(per_obs, np.float64)
        # Batched Welford merge (Chan parallel update — same form as
        # connectors.NormalizeObs): O(1) Python per batch.
        n_b = len(err)
        mean_b = err.mean()
        m2_b = ((err - mean_b) ** 2).sum()
        delta = mean_b - self._mean
        total = self._count + n_b
        self._mean += delta * n_b / total
        self._m2 += m2_b + delta ** 2 * self._count * n_b / total
        self._count = total
        std = max(np.sqrt(self._m2 / self._count), 1e-8)
        return (err / std).astype(np.float32)

    def state(self) -> dict:
        return {"pred": jax.device_get(self.pred),
                "opt": jax.device_get(self.opt_state),
                "norm": (self._count, self._mean, self._m2)}

    def set_state(self, st: dict) -> None:
        self.pred = jax.tree.map(jnp.asarray, st["pred"])
        if "opt" in st:  # continue the SAME Adam trajectory
            self.opt_state = jax.tree.map(jnp.asarray, st["opt"])
        else:
            self.opt_state = self.tx.init(self.pred)
        self._count, self._mean, self._m2 = st["norm"]
