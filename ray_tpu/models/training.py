"""Sharded train-step factory: TrainState + optimizer + jit wiring.

The reference's training substrate is torch DDP/FSDP wrapped per-process
(`train/torch/train_loop_utils.py:92-101`); the TPU-native equivalent is a
single jit-compiled SPMD program: gradients are averaged by XLA collectives
implied by the batch sharding, optimizer states inherit parameter shardings
(ZeRO-3 falls out of the `embed`→fsdp rule), and the whole step is donated
so params update in place in HBM.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_to_mesh_axes,
    tree_shardings,
)


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(
    learning_rate: float = 3e-4,
    *,
    warmup_steps: int = 100,
    total_steps: Optional[int] = None,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    moment_dtype: Any = None,
) -> optax.GradientTransformation:
    """AdamW with warmup(+cosine when total_steps given) and global-norm
    clipping. `moment_dtype=jnp.bfloat16` halves optimizer HBM — the
    standard single-chip-budget trade."""
    if total_steps is not None:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    elif warmup_steps > 0:
        schedule = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.scale_by_adam(b1=b1, b2=b2, mu_dtype=moment_dtype),
        optax.add_decayed_weights(
            weight_decay,
            mask=lambda params: jax.tree.map(lambda p: p.ndim > 1, params),
        ),
        optax.scale_by_learning_rate(schedule),
    )


def init_train_state(params, tx: optax.GradientTransformation) -> TrainState:
    """Build a TrainState from already-sharded params; optimizer moments
    are created inside jit and inherit the parameter shardings
    (computation-follows-data)."""
    opt_state = jax.jit(tx.init)(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    mesh=None,
    rules=DEFAULT_RULES,
    batch_logical: Any = None,
    donate: bool = True,
) -> Callable:
    """Returns jitted `(state, batch) -> (state, metrics)`.

    `loss_fn(params, batch) -> (scalar_loss, metrics_dict)`.
    `batch_logical`: pytree of logical-axis tuples matching `batch` (e.g.
    `{"tokens": ("batch", "seq"), ...}`); defaults to sharding every leaf's
    leading dim over ("data","fsdp").
    """

    def step_fn(state: TrainState, batch):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, metrics), grads = grad_fn(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(state.step + 1, params, opt_state), metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    def batch_shardings(batch):
        if batch_logical is not None:
            return tree_shardings(mesh, batch_logical, rules)
        spec = logical_to_mesh_axes(("batch",), rules)
        return jax.tree.map(lambda _: NamedSharding(mesh, spec), batch)

    jitted = jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    @functools.wraps(step_fn)
    def wrapper(state, batch):
        shardings = batch_shardings(batch)
        batch = jax.tree.map(
            lambda x, s: x if getattr(x, "sharding", None) == s
            else jax.device_put(x, s),
            batch, shardings)
        return jitted(state, batch)

    return wrapper


def make_eval_step(loss_fn: Callable, *, mesh=None,
                   rules=DEFAULT_RULES) -> Callable:
    def eval_fn(params, batch):
        _, metrics = loss_fn(params, batch)
        return metrics

    return jax.jit(eval_fn)


def state_shardings(cfg_logical_axes, mesh, tx, params_abstract,
                    rules=DEFAULT_RULES):
    """Shardings pytree for a full TrainState (params + matching optimizer
    moments + replicated scalars) — used when restoring checkpoints
    directly onto a mesh."""
    param_sh = tree_shardings(mesh, cfg_logical_axes, rules)
    opt_abstract = jax.eval_shape(tx.init, params_abstract)
    replicated = NamedSharding(mesh, P())

    param_leaves = jax.tree.leaves(params_abstract)
    shape_to_sh = {}
    for leaf, sh in zip(param_leaves, jax.tree.leaves(param_sh)):
        shape_to_sh.setdefault(leaf.shape, sh)

    def match(leaf):
        return shape_to_sh.get(leaf.shape, replicated)

    opt_sh = jax.tree.map(match, opt_abstract)
    return TrainState(step=replicated, params=param_sh, opt_state=opt_sh)
