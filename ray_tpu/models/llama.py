"""Llama-3-family decoder-only LM, written TPU-first.

Design choices (vs. a torch port):
- Parameters are a plain pytree of arrays with a parallel pytree of
  *logical axis names* (`param_logical_axes`) — sharding is data, not code.
- Layers are stacked along a leading axis and driven by `lax.scan` with
  `jax.checkpoint` on the body: O(1) compile time in depth, per-layer
  rematerialization for HBM.
- Attention is pluggable: Pallas flash kernel (single-device sequence),
  ring attention or Ulysses over the ``seq`` mesh axis (context parallel),
  or the reference einsum (CPU tests).
- bf16 params/activations, f32 for softmax/norm statistics — the MXU path.

Config presets follow the Llama-3 family (rope_theta 500000, GQA,
SwiGLU with the 8/3 expansion).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import flash_attention, attention_reference
from ray_tpu.ops.cross_entropy import (fused_linear_cross_entropy,
                                       softmax_cross_entropy)
from ray_tpu.ops.norms import rms_norm_reference
from ray_tpu.ops.rope import (apply_rope, rope_frequencies,
                              rope_from_positions)
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    tree_shardings,
    with_logical_constraint,
)
from ray_tpu.parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # "auto" | "flash" | "ring" | "ulysses" | "reference"
    attention: str = "auto"
    # False | True (save attn out/lse only) | "gate" (+silu(w1) act) |
    # "mlp" (+both ffn acts). Validated in forward_hidden.
    remat: Any = True
    # Fuse the output projection into the CE loss (logits never
    # materialized). Auto-disabled when the vocab dim is sharded.
    fused_ce: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        d, h, l, v = self.dim, self.hidden_dim, self.n_layers, self.vocab_size
        per_layer = (
            d * self.n_heads * self.head_dim          # wq
            + 2 * d * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * d         # wo
            + 3 * d * h                                # w1, w2, w3
            + 2 * d                                    # norms
        )
        embeds = v * d * (1 if self.tie_embeddings else 2)
        return l * per_layer + embeds + d

    # -- presets ---------------------------------------------------------

    @staticmethod
    def debug() -> "LlamaConfig":
        return LlamaConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                           n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                           dtype=jnp.float32, remat=False)

    @staticmethod
    def llama3_1b() -> "LlamaConfig":
        # Llama-3.2-1B: 1.23B params, tied embeddings.
        return LlamaConfig(vocab_size=128256, dim=2048, n_layers=16,
                           n_heads=32, n_kv_heads=8, hidden_dim=8192,
                           tie_embeddings=True)

    @staticmethod
    def llama3_3b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, dim=3072, n_layers=28,
                           n_heads=24, n_kv_heads=8, hidden_dim=8192,
                           tie_embeddings=True)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()  # defaults are 8B

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                           hidden_dim=28672)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def _init_layer(cfg: LlamaConfig, key) -> Dict[str, Any]:
    d, hd = cfg.dim, cfg.head_dim
    k1, k2, k3, k4, k5, k6, k7 = jax.random.split(key, 7)
    scale = d ** -0.5
    hidden_scale = cfg.hidden_dim ** -0.5
    init = jax.nn.initializers.normal(stddev=0.02)
    return {
        "attn_norm": jnp.ones(d, cfg.dtype),
        "wq": init(k1, (d, cfg.n_heads, hd), cfg.dtype),
        "wk": init(k2, (d, cfg.n_kv_heads, hd), cfg.dtype),
        "wv": init(k3, (d, cfg.n_kv_heads, hd), cfg.dtype),
        "wo": (init(k4, (cfg.n_heads, hd, d), cfg.dtype) * scale),
        "mlp_norm": jnp.ones(d, cfg.dtype),
        "w1": init(k5, (d, cfg.hidden_dim), cfg.dtype),
        "w3": init(k6, (d, cfg.hidden_dim), cfg.dtype),
        "w2": (init(k7, (cfg.hidden_dim, d), cfg.dtype) * hidden_scale),
    }


def init_params(cfg: LlamaConfig, rng) -> Dict[str, Any]:
    k_embed, k_out, k_layers = jax.random.split(rng, 3)
    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    layers = jax.vmap(functools.partial(_init_layer, cfg))(layer_keys)
    params = {
        "embed": jax.nn.initializers.normal(0.02)(
            k_embed, (cfg.vocab_size, cfg.dim), cfg.dtype),
        "layers": layers,
        "final_norm": jnp.ones(cfg.dim, cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["out"] = jax.nn.initializers.normal(0.02)(
            k_out, (cfg.dim, cfg.vocab_size), cfg.dtype)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Same structure as `init_params` output, with logical-axis tuples as
    leaves. Leading `None` on layer params is the scanned layer axis."""
    layer = {
        "attn_norm": (None, "norm"),
        "wq": (None, "embed", "heads", "head_dim"),
        "wk": (None, "embed", "kv_heads", "head_dim"),
        "wv": (None, "embed", "kv_heads", "head_dim"),
        "wo": (None, "heads", "head_dim", "embed"),
        "mlp_norm": (None, "norm"),
        "w1": (None, "embed", "mlp"),
        "w3": (None, "embed", "mlp"),
        "w2": (None, "mlp", "embed"),
    }
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["out"] = ("embed", "vocab")
    return axes


def init_params_sharded(cfg: LlamaConfig, mesh, rng,
                        rules=DEFAULT_RULES) -> Dict[str, Any]:
    """Initialize directly into sharded device buffers (no host staging —
    required for models bigger than host/chip memory)."""
    shardings = tree_shardings(mesh, param_logical_axes(cfg), rules)
    fn = jax.jit(functools.partial(init_params, cfg),
                 out_shardings=shardings)
    return fn(rng)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _attention(cfg: LlamaConfig, q, k, v, mesh, rules):
    """q: [B,S,H,D]; k/v: [B,S,Hkv,D] → [B,S,H,D]."""
    impl = cfg.attention
    if impl == "auto":
        seq_parallel = mesh is not None and mesh.shape.get("seq", 1) > 1
        if seq_parallel:
            impl = "ring"
        else:
            try:
                on_tpu = jax.devices()[0].platform == "tpu"
            except Exception:  # pragma: no cover
                on_tpu = False
            impl = "flash" if on_tpu else "reference"
    if impl == "flash":
        return flash_attention(q, k, v, causal=True)
    if impl in ("ring", "ulysses"):
        # Ring/Ulysses currently take equal head counts; expand GQA KV
        # heads (cheap relative to long-context attention itself).
        rep = cfg.n_heads // cfg.n_kv_heads
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        fn = ring_attention if impl == "ring" else ulysses_attention
        return fn(q, k, v, mesh=mesh, axis_name="seq", causal=True)
    # reference
    rep = cfg.n_heads // cfg.n_kv_heads
    out = attention_reference(
        q.transpose(0, 2, 1, 3),
        jnp.repeat(k, rep, axis=2).transpose(0, 2, 1, 3),
        jnp.repeat(v, rep, axis=2).transpose(0, 2, 1, 3),
        True, cfg.head_dim ** -0.5)
    return out.transpose(0, 2, 1, 3)


def layer_fn(cfg: LlamaConfig, mesh, rules, cos, sin, x, lp, positions):
    """One transformer block. x: [B, S, D]."""
    h = rms_norm_reference(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    q = with_logical_constraint(q, "batch", "seq", "heads", "head_dim",
                                mesh=mesh, rules=rules)
    attn = _attention(cfg, q, k, v, mesh, rules)
    x = x + jnp.einsum("bshk,hkd->bsd", attn.astype(cfg.dtype), lp["wo"])
    h2 = rms_norm_reference(x, lp["mlp_norm"], cfg.norm_eps)
    # Named for selective remat: cfg.remat="mlp" saves these two (the
    # dominant recompute cost) while still rematerializing the rest.
    gate = checkpoint_name(
        jax.nn.silu(jnp.einsum("bsd,df->bsf", h2, lp["w1"])), "ffn_gate")
    up = checkpoint_name(
        jnp.einsum("bsd,df->bsf", h2, lp["w3"]), "ffn_up")
    ff = with_logical_constraint(gate * up, "batch", "seq", "mlp",
                                 mesh=mesh, rules=rules)
    x = x + jnp.einsum("bsf,fd->bsd", ff, lp["w2"])
    x = with_logical_constraint(x, "batch", "seq", "act_embed",
                                mesh=mesh, rules=rules)
    return x


# Tables up to this size are replicated before the token gather: with the
# table left vocab-sharded the SPMD partitioner partitions the gather on
# the vocab dim and then "involuntarily rematerializes" (fully replicates)
# the gathered activations to reach the activation sharding, so one table
# transition is strictly cheaper. Past the threshold (large-vocab TP
# configs) replication would cost vocab*embed bytes of HBM per device, so
# the table keeps its embed-dim shard instead — the gather then moves only
# the looked-up rows, at the price of an all-gather over the activations.
_EMBED_REPLICATE_MAX_BYTES = 1 << 27  # 128 MiB


def _embed_lookup(embed, tokens, mesh, rules):
    small = embed.size * embed.dtype.itemsize <= _EMBED_REPLICATE_MAX_BYTES
    axes = (None, None) if small else (None, "embed")
    embed = with_logical_constraint(embed, *axes, mesh=mesh, rules=rules)
    return embed[tokens]


def forward_hidden(params, tokens, cfg: LlamaConfig, *, mesh=None,
                   rules=DEFAULT_RULES, positions=None):
    """tokens: [B, S] int32 → final-norm hidden states [B, S, D]
    (cfg.dtype) — the stack without the output projection, so the loss
    can fuse projection+CE (`fused_linear_cross_entropy`)."""
    # With context parallelism each shard sees a sequence chunk; RoPE
    # must use global positions, which the caller passes in. Default is
    # the unsharded arange. For explicit positions, cos/sin come from an
    # elementwise compute (no table gather) hoisted out of the layer
    # loop and constrained to the activation sharding — the gather form
    # makes the SPMD partitioner replicate-and-repartition the looked-up
    # values every step ("involuntary full rematerialization").
    if positions is not None:
        cos, sin = rope_from_positions(positions, cfg.head_dim,
                                       cfg.rope_theta)
        cos = with_logical_constraint(cos, "batch", "seq", None,
                                      mesh=mesh, rules=rules)
        sin = with_logical_constraint(sin, "batch", "seq", None,
                                      mesh=mesh, rules=rules)
        positions = None
    else:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
    x = _embed_lookup(params["embed"], tokens, mesh, rules).astype(cfg.dtype)
    x = with_logical_constraint(x, "batch", "seq", "act_embed",
                                mesh=mesh, rules=rules)

    body = functools.partial(layer_fn, cfg, mesh, rules, cos, sin)

    def scan_body(x, lp):
        return body(x, lp, positions), None

    if cfg.remat:
        # Save the flash-attention output + logsumexp across the remat
        # boundary: the backward then recomputes only the cheap projections
        # (for the q/k/v residuals) and never re-runs the forward attention
        # kernel. ~37MB/layer at 4x2048 — a large step-time win for a small
        # slice of HBM. remat="mlp" additionally saves the two MLP hidden
        # activations (the dominant recompute FLOPs; ~268MB/layer at
        # 4x2048) — worth it when the fused-CE loss path leaves the HBM
        # headroom.
        if cfg.remat not in (True, "mlp", "gate"):
            raise ValueError(
                f"remat={cfg.remat!r}: expected False, True, 'gate', or "
                "'mlp' (a typo here would silently train with attn-only "
                "checkpointing)")
        names = ["flash_out", "flash_lse"]
        if cfg.remat == "mlp":
            names += ["ffn_gate", "ffn_up"]
        elif cfg.remat == "gate":  # half the HBM of "mlp"
            names += ["ffn_gate"]
        scan_body = jax.checkpoint(
            scan_body,
            policy=jax.checkpoint_policies.save_only_these_names(*names))
    x, _ = lax.scan(scan_body, x, params["layers"])
    return rms_norm_reference(x, params["final_norm"], cfg.norm_eps)


def forward(params, tokens, cfg: LlamaConfig, *, mesh=None,
            rules=DEFAULT_RULES, positions=None):
    """tokens: [B, S] int32 → logits [B, S, vocab] (cfg.dtype)."""
    x = forward_hidden(params, tokens, cfg, mesh=mesh, rules=rules,
                       positions=positions)
    out_w = params["embed"].T if cfg.tie_embeddings else params["out"]
    logits = jnp.einsum("bsd,dv->bsv", x, out_w.astype(cfg.dtype))
    return with_logical_constraint(logits, "batch", "seq", "vocab",
                                   mesh=mesh, rules=rules)


def _vocab_sharded(mesh, rules) -> bool:
    if mesh is None:
        return False
    axis = dict(rules).get("vocab")
    if axis is None:
        return False
    axes = axis if isinstance(axis, tuple) else (axis,)
    size = 1
    for a in axes:
        size *= mesh.shape.get(a, 1)
    return size > 1


def loss_fn(params, batch, cfg: LlamaConfig, *, mesh=None,
            rules=DEFAULT_RULES):
    """batch: {"tokens": [B,S], "targets": [B,S], optional "mask": [B,S],
    optional "positions": [B,S]}. Returns (mean loss f32, metrics dict)."""
    b, s = batch["tokens"].shape
    if cfg.fused_ce and not _vocab_sharded(mesh, rules):
        # Fused projection+CE: the [tokens, vocab] logits tensor is never
        # materialized (the largest single activation at 128k vocab).
        x = forward_hidden(params, batch["tokens"], cfg, mesh=mesh,
                           rules=rules, positions=batch.get("positions"))
        out_w = params["embed"].T if cfg.tie_embeddings else params["out"]
        losses = fused_linear_cross_entropy(
            x.reshape(b * s, cfg.dim), out_w.astype(cfg.dtype),
            batch["targets"].reshape(b * s))
    else:
        logits = forward(params, batch["tokens"], cfg, mesh=mesh,
                         rules=rules, positions=batch.get("positions"))
        losses = softmax_cross_entropy(
            logits.reshape(b * s, cfg.vocab_size),
            batch["targets"].reshape(b * s))
    losses = losses.reshape(b, s)
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones((b, s), jnp.float32)
    total = jnp.maximum(mask.sum(), 1.0)
    loss = (losses * mask).sum() / total
    return loss, {"loss": loss, "tokens": total,
                  "perplexity": jnp.exp(loss)}


# ---------------------------------------------------------------------------
# KV-cache inference path (prefill + decode) — used by ray_tpu.serve.llm
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: LlamaConfig, n_slots: int, max_seq: int,
                  dtype=None) -> Dict[str, Any]:
    """Slot-based KV cache: [layers, slots, max_seq, kv_heads, head_dim].
    One slot per in-flight sequence; continuous batching admits/retires
    requests per slot without touching the others (static shapes → one
    compiled decode program)."""
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, n_slots, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_attention(cfg, q, k_cache, v_cache, q_positions):
    """q: [B, T, H, D]; caches: [B, S, Hkv, D]; q_positions: [B, T]
    absolute positions. Causal over absolute key positions."""
    b, t, h, d = q.shape
    s = k_cache.shape[1]
    rep = cfg.n_heads // cfg.n_kv_heads
    k = jnp.repeat(k_cache, rep, axis=2)
    v = jnp.repeat(v_cache, rep, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    key_pos = jnp.arange(s)
    mask = key_pos[None, None, :] <= q_positions[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def forward_with_cache(params, tokens, cfg: LlamaConfig, cache,
                       start_pos):
    """Incremental forward: runs `tokens` [B, T] starting at per-sequence
    absolute offsets `start_pos` [B], reading/writing the KV cache.
    Returns (logits [B, T, vocab], new_cache). Works for prefill (T =
    prompt length) and decode (T = 1) with one code path.
    """
    b, t = tokens.shape
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)
    positions = start_pos[:, None] + jnp.arange(t)[None, :]  # [B, T]
    x = params["embed"][tokens].astype(cfg.dtype)

    def write_cache(cache_b, new_b, start_b):
        # cache_b: [S, Hkv, D]; new_b: [T, Hkv, D]
        return lax.dynamic_update_slice(
            cache_b, new_b.astype(cache_b.dtype), (start_b, 0, 0))

    def layer(x, scanned):
        lp, k_cache_l, v_cache_l = scanned
        h = rms_norm_reference(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        k_cache_l = jax.vmap(write_cache)(k_cache_l, k, start_pos)
        v_cache_l = jax.vmap(write_cache)(v_cache_l, v, start_pos)
        attn = _cached_attention(cfg, q, k_cache_l, v_cache_l, positions)
        x = x + jnp.einsum("bshk,hkd->bsd", attn.astype(cfg.dtype),
                           lp["wo"])
        h2 = rms_norm_reference(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h2, lp["w1"]))
        up = jnp.einsum("bsd,df->bsf", h2, lp["w3"])
        x = x + jnp.einsum("bsf,fd->bsd", gate * up, lp["w2"])
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm_reference(x, params["final_norm"], cfg.norm_eps)
    out_w = params["embed"].T if cfg.tie_embeddings else params["out"]
    logits = jnp.einsum("bsd,dv->bsv", x, out_w.astype(cfg.dtype))
    return logits, {"k": k_new, "v": v_new}
