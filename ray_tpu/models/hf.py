"""HuggingFace checkpoint interop for the Llama family.

Converts `transformers` Llama weights (safetensors/torch state dict) into
this framework's param pytree — the bridge for serving/fine-tuning
published checkpoints. Conversion is pure tensor reshaping:

- `q_proj.weight` [H*hd, D] → wq [D, H, hd] (transpose + split heads)
- `gate/up/down_proj` → w1/w3/w2 (transposed)
- `embed_tokens` → embed; `lm_head` → out (absent when tied)

HF stores Q/K in the *interleaved* RoPE convention; our kernels use the
split-half convention, so Q/K weights are permuted accordingly (standard
`permute` from the transformers conversion script, inverted).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config) -> LlamaConfig:
    """Map a `transformers.LlamaConfig` to our LlamaConfig."""
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        hidden_dim=hf_config.intermediate_size,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 8192),
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )


def _unpermute_rope(w: np.ndarray, n_heads: int, dim: int) -> np.ndarray:
    """HF interleaved → split-half convention. w: [n_heads*hd, dim]."""
    hd = w.shape[0] // n_heads
    w = w.reshape(n_heads, 2, hd // 2, dim)
    return w.transpose(0, 2, 1, 3).reshape(n_heads * hd, dim)


def params_from_hf_state_dict(state_dict: Dict[str, Any],
                              cfg: LlamaConfig,
                              dtype=None) -> Dict[str, Any]:
    """Torch/numpy state dict → param pytree (layers stacked for scan)."""
    dtype = dtype or cfg.dtype

    def tensor(name) -> np.ndarray:
        t = state_dict[name]
        if hasattr(t, "detach"):
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t, np.float32)

    hd = cfg.head_dim
    layers: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w1", "w2", "w3")}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        wq = _unpermute_rope(tensor(p + "self_attn.q_proj.weight"),
                             cfg.n_heads, cfg.dim)
        wk = _unpermute_rope(tensor(p + "self_attn.k_proj.weight"),
                             cfg.n_kv_heads, cfg.dim)
        wv = tensor(p + "self_attn.v_proj.weight")
        wo = tensor(p + "self_attn.o_proj.weight")
        layers["attn_norm"].append(
            tensor(p + "input_layernorm.weight"))
        layers["wq"].append(
            wq.T.reshape(cfg.dim, cfg.n_heads, hd))
        layers["wk"].append(
            wk.T.reshape(cfg.dim, cfg.n_kv_heads, hd))
        layers["wv"].append(
            wv.T.reshape(cfg.dim, cfg.n_kv_heads, hd))
        layers["wo"].append(
            wo.T.reshape(cfg.n_heads, hd, cfg.dim))
        layers["mlp_norm"].append(
            tensor(p + "post_attention_layernorm.weight"))
        layers["w1"].append(tensor(p + "mlp.gate_proj.weight").T)
        layers["w3"].append(tensor(p + "mlp.up_proj.weight").T)
        layers["w2"].append(tensor(p + "mlp.down_proj.weight").T)

    params = {
        "embed": jnp.asarray(tensor("model.embed_tokens.weight"), dtype),
        "layers": {k: jnp.asarray(np.stack(v), dtype)
                   for k, v in layers.items()},
        "final_norm": jnp.asarray(tensor("model.norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["out"] = jnp.asarray(tensor("lm_head.weight").T, dtype)
    return params


def load_llama_from_hf(model_name_or_path: str, *,
                       dtype=None,
                       mesh=None, rules=None):
    """Load a transformers Llama checkpoint into (cfg, params); with a
    mesh, parameters are placed sharded."""
    import transformers

    hf_model = transformers.AutoModelForCausalLM.from_pretrained(
        model_name_or_path)
    cfg = config_from_hf(hf_model.config)
    params = params_from_hf_state_dict(hf_model.state_dict(), cfg,
                                       dtype=dtype)
    if mesh is not None:
        from ray_tpu.models.llama import param_logical_axes
        from ray_tpu.parallel.sharding import DEFAULT_RULES, shard_pytree

        params = shard_pytree(params, mesh, param_logical_axes(cfg),
                              rules or DEFAULT_RULES)
    return cfg, params
