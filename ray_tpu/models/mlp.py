"""Small MLP classifier — the fashion-MNIST baseline workload.

Reference parity target: the AIR torch MNIST benchmark
(`release/air_tests/air_benchmarks/workloads/torch_benchmark.py`), which
asserts DDP throughput parity. Here the same network is a jit-compiled JAX
function whose data parallelism is a mesh axis, used by the Train-layer
tests and `bench.py`'s CPU fallback.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Tuple[int, ...] = (128, 128)
    n_classes: int = 10
    dtype: Any = jnp.float32


def mlp_init(cfg: MLPConfig, rng):
    dims = (cfg.in_dim,) + cfg.hidden + (cfg.n_classes,)
    keys = jax.random.split(rng, len(dims) - 1)
    params = []
    for k, (d_in, d_out) in zip(keys, zip(dims[:-1], dims[1:])):
        w = jax.random.normal(k, (d_in, d_out), cfg.dtype) * (d_in ** -0.5)
        b = jnp.zeros(d_out, cfg.dtype)
        params.append({"w": w, "b": b})
    return params


def mlp_forward(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params, batch):
    logits = mlp_forward(params, batch["x"])
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    acc = (logits.argmax(-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}
