"""Mixture-of-Experts Llama variant: expert parallelism over the
``expert`` mesh axis.

No reference equivalent (the reference has no model code); this exists so
EP is a first-class, exercised parallelism axis (SURVEY.md §2 parallelism
inventory calls EP "absent entirely" upstream — our charter adds it).

Routing: top-k softmax gating with a load-balancing auxiliary loss
(Switch-Transformer style). Dispatch is the dense-masked formulation:
every expert runs over all tokens with gates zeroing non-selected
contributions — compute-redundant by factor E/k but perfectly shardable
by GSPMD over the expert axis (each device computes only its local
experts; token activations stay put; one psum combines). The
capacity-based sparse dispatch (all-to-all) is the planned optimization
once the EP axis spans real slices.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import (
    LlamaConfig,
    _attention,
    _embed_lookup,
    _init_layer,
)
from ray_tpu.ops.cross_entropy import softmax_cross_entropy
from ray_tpu.ops.norms import rms_norm_reference
from ray_tpu.ops.rope import (apply_rope, rope_frequencies,
                              rope_from_positions)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    tree_shardings,
    with_logical_constraint,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    n_experts: int = 8
    n_experts_per_token: int = 2
    aux_loss_coeff: float = 0.01

    @staticmethod
    def debug_moe() -> "MoEConfig":
        return MoEConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                         dtype=jnp.float32, remat=False, n_experts=4,
                         n_experts_per_token=2)

    @staticmethod
    def mixtral_8x7b() -> "MoEConfig":
        return MoEConfig(vocab_size=32000, dim=4096, n_layers=32,
                         n_heads=32, n_kv_heads=8, hidden_dim=14336,
                         rope_theta=1e6, n_experts=8,
                         n_experts_per_token=2)


def _init_moe_layer(cfg: MoEConfig, key) -> Dict[str, Any]:
    base = _init_layer(cfg, key)
    k_router, k1, k2, k3 = jax.random.split(jax.random.fold_in(key, 99), 4)
    init = jax.nn.initializers.normal(stddev=0.02)
    e, d, h = cfg.n_experts, cfg.dim, cfg.hidden_dim
    # Replace the dense FFN with per-expert weights + a router.
    for dead in ("w1", "w2", "w3"):
        del base[dead]
    base["router"] = init(k_router, (d, e), cfg.dtype)
    base["we1"] = init(k1, (e, d, h), cfg.dtype)
    base["we3"] = init(k2, (e, d, h), cfg.dtype)
    base["we2"] = init(k3, (e, h, d), cfg.dtype) * (h ** -0.5)
    return base


def init_moe_params(cfg: MoEConfig, rng) -> Dict[str, Any]:
    k_embed, k_out, k_layers = jax.random.split(rng, 3)
    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    layers = jax.vmap(functools.partial(_init_moe_layer, cfg))(layer_keys)
    params = {
        "embed": jax.nn.initializers.normal(0.02)(
            k_embed, (cfg.vocab_size, cfg.dim), cfg.dtype),
        "layers": layers,
        "final_norm": jnp.ones(cfg.dim, cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["out"] = jax.nn.initializers.normal(0.02)(
            k_out, (cfg.dim, cfg.vocab_size), cfg.dtype)
    return params


def moe_param_logical_axes(cfg: MoEConfig) -> Dict[str, Any]:
    layer = {
        "attn_norm": (None, "norm"),
        "wq": (None, "embed", "heads", "head_dim"),
        "wk": (None, "embed", "kv_heads", "head_dim"),
        "wv": (None, "embed", "kv_heads", "head_dim"),
        "wo": (None, "heads", "head_dim", "embed"),
        "mlp_norm": (None, "norm"),
        "router": (None, "embed", None),
        "we1": (None, "expert", "embed", "mlp"),
        "we3": (None, "expert", "embed", "mlp"),
        "we2": (None, "expert", "mlp", "embed"),
    }
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["out"] = ("embed", "vocab")
    return axes


def init_moe_params_sharded(cfg: MoEConfig, mesh, rng,
                            rules=DEFAULT_RULES):
    shardings = tree_shardings(mesh, moe_param_logical_axes(cfg), rules)
    return jax.jit(functools.partial(init_moe_params, cfg),
                   out_shardings=shardings)(rng)


def _moe_ffn(cfg: MoEConfig, lp, x, mesh, rules):
    """x: [B, S, D] → ([B, S, D], aux_loss scalar)."""
    b, s, d = x.shape
    logits = jnp.einsum("bsd,de->bse", x, lp["router"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)            # [B, S, E]
    k = cfg.n_experts_per_token
    topk_vals, _ = lax.top_k(probs, k)
    threshold = topk_vals[..., -1:]
    gates = jnp.where(probs >= threshold, probs, 0.0)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    gates = gates.astype(cfg.dtype)                    # [B, S, E]

    # Load-balance aux loss: E * Σ_e fraction_tokens_e · mean_prob_e.
    token_frac = (gates > 0).astype(jnp.float32).mean(axis=(0, 1))
    prob_frac = probs.mean(axis=(0, 1))
    aux = cfg.n_experts * jnp.sum(token_frac * prob_frac)

    # Dense-masked expert computation, sharded over the expert axis.
    gate_x = jnp.einsum("bsd,edf->ebsf", x, lp["we1"])
    up_x = jnp.einsum("bsd,edf->ebsf", x, lp["we3"])
    hidden = jax.nn.silu(gate_x) * up_x                # [E, B, S, F]
    hidden = with_logical_constraint(hidden, "expert", "batch", "seq",
                                     "mlp", mesh=mesh, rules=rules)
    per_expert = jnp.einsum("ebsf,efd->ebsd", hidden, lp["we2"])
    out = jnp.einsum("ebsd,bse->bsd", per_expert,
                     gates.transpose(0, 1, 2))
    return out, aux


def moe_forward(params, tokens, cfg: MoEConfig, *, mesh=None,
                rules=DEFAULT_RULES, positions=None):
    """Returns (logits [B,S,V], total aux loss)."""
    # Same SPMD hygiene as llama.forward: explicit positions → elementwise
    # cos/sin sharded with the activations (no table gather), and the
    # embed table size-gated replicated/sharded before the token gather
    # (_embed_lookup) so the partitioner doesn't fully rematerialize the
    # gathered activations.
    if positions is not None:
        cos, sin = rope_from_positions(positions, cfg.head_dim,
                                       cfg.rope_theta)
        cos = with_logical_constraint(cos, "batch", "seq", None,
                                      mesh=mesh, rules=rules)
        sin = with_logical_constraint(sin, "batch", "seq", None,
                                      mesh=mesh, rules=rules)
        positions = None
    else:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
    x = _embed_lookup(params["embed"], tokens, mesh, rules).astype(cfg.dtype)
    x = with_logical_constraint(x, "batch", "seq", "act_embed",
                                mesh=mesh, rules=rules)

    def layer(carry, lp):
        x, aux_acc = carry
        h = rms_norm_reference(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
        k_ = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
        q = apply_rope(q, cos, sin, positions)
        k_ = apply_rope(k_, cos, sin, positions)
        attn = _attention(cfg, q, k_, v, mesh, rules)
        x = x + jnp.einsum("bshk,hkd->bsd", attn.astype(cfg.dtype),
                           lp["wo"])
        h2 = rms_norm_reference(x, lp["mlp_norm"], cfg.norm_eps)
        ffn_out, aux = _moe_ffn(cfg, lp, h2, mesh, rules)
        x = x + ffn_out
        x = with_logical_constraint(x, "batch", "seq", "act_embed",
                                    mesh=mesh, rules=rules)
        return (x, aux_acc + aux), None

    body = layer
    if cfg.remat:
        body = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.nothing_saveable)
    (x, aux_total), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                                 params["layers"])
    x = rms_norm_reference(x, params["final_norm"], cfg.norm_eps)
    out_w = params["embed"].T if cfg.tie_embeddings else params["out"]
    logits = jnp.einsum("bsd,dv->bsv", x, out_w.astype(cfg.dtype))
    return logits, aux_total / cfg.n_layers


def moe_loss_fn(params, batch, cfg: MoEConfig, *, mesh=None,
                rules=DEFAULT_RULES):
    logits, aux = moe_forward(params, batch["tokens"], cfg, mesh=mesh,
                              rules=rules,
                              positions=batch.get("positions"))
    b, s, v = logits.shape
    losses = softmax_cross_entropy(
        logits.reshape(b * s, v), batch["targets"].reshape(b * s))
    ce = losses.mean()
    loss = ce + cfg.aux_loss_coeff * aux
    return loss, {"loss": loss, "ce_loss": ce, "aux_loss": aux}
