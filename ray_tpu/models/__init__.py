"""Model zoo, TPU-first.

Pure-JAX pytree models (no framework lock-in) annotated with the logical
sharding axes from `ray_tpu.parallel.sharding`, so the same model code runs
single-chip, FSDP, tensor-parallel, and context-parallel by swapping mesh +
rules. The reference has no model zoo of its own (it wraps torch modules);
these exist because the TPU framework's Train/Serve/RL layers need
first-class compiled models to schedule.

- ``llama`` — Llama-3-family decoder LM (GQA, RoPE, SwiGLU), the flagship
- ``mlp``   — small MLP classifier (the fashion-MNIST baseline workload)
- ``training`` — TrainState + sharded train-step factory
"""

from ray_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_params,
    init_params_sharded,
    forward,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_forward  # noqa: F401
from ray_tpu.models.training import (  # noqa: F401
    TrainState,
    make_optimizer,
    make_train_step,
    init_train_state,
)
