"""Per-chip HBM planning + abstract shape-check for large configs.

Reference role: the capacity planning the reference's release configs
encode implicitly (`release/benchmarks/` cluster templates pick machine
shapes per model size). Here it's a first-class tool: given a
LlamaConfig and a mesh shape, account parameter / optimizer / gradient
/ activation bytes per chip against the HBM budget, and prove the
sharded train step TRACES consistently on a virtual mesh of that shape
via ``jax.eval_shape`` — no weights materialized, no compilation, so an
8B/70B plan runs in seconds on a CPU host.

``plan_llama`` is what `__graft_entry__.dryrun_multichip` runs for the
Llama-3-8B-on-v5e-64 north star (BASELINE.md): the measured config on
this 1-chip host is 1.24B, but the 8B layout is shape-checked every
round.
"""

from __future__ import annotations

from typing import Any, Dict

HBM_PER_CHIP = {
    "v5e": 16.0,       # GiB
    "v5p": 95.0,
    "v4": 32.0,
}


def _gib(n_bytes: float) -> float:
    return n_bytes / (1 << 30)


def plan_llama(cfg, mesh_shape: Dict[str, int], *, batch_per_chip: int,
               seq_len: int, chip: str = "v5e",
               moment_dtype_bytes: int = 4,
               remat: Any = True) -> Dict[str, Any]:
    """Analytic per-chip HBM budget for training `cfg` on a mesh of
    `mesh_shape` (e.g. {"data": 1, "fsdp": 16, "tensor": 4} = 64 chips).

    Accounting (bf16 params/grads, fp32-or-bf16 Adam moments):
    - params:   2 bytes, sharded over fsdp*tensor
    - grads:    2 bytes, same sharding (live during the update)
    - adam:     2 moments * moment_dtype_bytes, same sharding
    - activations: with remat=True the scan saves, per layer, the
      residual-stream carry plus the flash out+lse; the backward's
      working set adds one layer's full activations. "mlp"/"gate"
      additionally save the ffn hiddens.
    - loss: fused CE never materializes [B, S, V] logits; the fp32
      hidden row chunk is negligible.
    """
    n_chips = 1
    for v in mesh_shape.values():
        n_chips *= v
    shard = mesh_shape.get("fsdp", 1) * mesh_shape.get("tensor", 1)
    p = cfg.num_params()
    param_b = 2 * p / shard
    grad_b = 2 * p / shard
    opt_b = 2 * moment_dtype_bytes * p / shard

    b, s, d, h = batch_per_chip, seq_len, cfg.dim, cfg.hidden_dim
    heads, hd = cfg.n_heads, cfg.head_dim
    # per-layer SAVED bytes under the remat policy (bf16 = 2 bytes)
    carry = b * s * d * 2
    flash = b * s * heads * hd * 2 + b * heads * s * 4  # out + lse(fp32)
    saved = carry + flash
    if remat == "gate":
        saved += b * s * h * 2
    elif remat == "mlp":
        saved += 2 * b * s * h * 2
    elif not remat:
        # everything live: q,k,v,attn,out,2 norms,3 ffn ~ rough 12x carry
        saved = carry * 6 + flash + 3 * b * s * h * 2
    act_b = saved * cfg.n_layers
    # backward working set: one layer recomputed in full
    work_b = carry * 6 + flash + 3 * b * s * h * 2
    # embedding table (replicated below the gather threshold, else
    # embed-sharded) + fp32 CE chunk
    embed_bytes = cfg.vocab_size * d * 2
    embed_b = embed_bytes if embed_bytes <= (1 << 27) \
        else embed_bytes / mesh_shape.get("tensor", 1)

    total_b = param_b + grad_b + opt_b + act_b + work_b + embed_b
    hbm = HBM_PER_CHIP[chip] * (1 << 30)
    return {
        "config": f"{p/1e9:.2f}B params",
        "mesh": dict(mesh_shape),
        "chips": n_chips,
        "chip": chip,
        "batch_per_chip": b,
        "seq_len": s,
        "per_chip_gib": {
            "params": round(_gib(param_b), 3),
            "grads": round(_gib(grad_b), 3),
            "optimizer": round(_gib(opt_b), 3),
            "activations_saved": round(_gib(act_b), 3),
            "backward_working_set": round(_gib(work_b), 3),
            "embedding": round(_gib(embed_b), 3),
            "total": round(_gib(total_b), 3),
        },
        "hbm_gib": HBM_PER_CHIP[chip],
        "utilization": round(total_b / hbm, 3),
        "fits": total_b < hbm * 0.92,  # leave XLA scratch headroom
        "global_tokens_per_step": b * s * mesh_shape.get("data", 1)
        * mesh_shape.get("fsdp", 1),
    }


def shape_check_llama(cfg, mesh_shape: Dict[str, int],
                      *, batch_per_chip: int, seq_len: int,
                      moment_dtype=None) -> Dict[str, Any]:
    """Abstract-eval the FULL sharded train step for `cfg` on a virtual
    mesh of `mesh_shape` — params, optimizer state, and one step's
    outputs as ShapeDtypeStructs with their NamedShardings resolved.
    Nothing is allocated; tracing catches every shape/sharding
    inconsistency the real run would hit.

    Requires enough (virtual) devices for the mesh — run under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama, training
    from ray_tpu.parallel import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(**mesh_shape))
    n_chips = int(np.prod(list(mesh.shape.values())))

    def init_fn(rng):
        return llama.init_params(cfg, rng)

    params_abs = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    tx = training.make_optimizer(3e-4, moment_dtype=moment_dtype)
    state_abs = jax.eval_shape(
        lambda p: training.init_train_state(p, tx), params_abs)
    shardings = training.state_shardings(
        llama.param_logical_axes(cfg), mesh, tx, params_abs)

    data_shards = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    global_batch = batch_per_chip * data_shards
    batch_abs = {
        "tokens": jax.ShapeDtypeStruct((global_batch, seq_len),
                                       jnp.int32),
        "targets": jax.ShapeDtypeStruct((global_batch, seq_len),
                                        jnp.int32),
    }

    def step(state, batch):
        def loss(p, b):
            return llama.loss_fn(p, b, cfg, mesh=mesh)

        grads = jax.grad(lambda p: loss(p, batch),
                         has_aux=True)(state.params)[0]
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        import optax

        params = optax.apply_updates(state.params, updates)
        return state._replace(params=params, opt_state=opt_state,
                              step=state.step + 1)

    out_abs = jax.eval_shape(step, state_abs, batch_abs)
    n_leaves = len(jax.tree.leaves(out_abs))
    param_count = sum(int(np.prod(l.shape))
                     for l in jax.tree.leaves(params_abs))
    return {
        "chips": n_chips,
        "mesh": dict(mesh.shape),
        "params": param_count,
        "global_batch": global_batch,
        "seq_len": seq_len,
        "state_leaves": n_leaves,
        "sharding_resolved": len(jax.tree.leaves(shardings)) > 0,
        "ok": True,
    }
