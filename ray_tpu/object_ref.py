"""ObjectRef: a handle to a (possibly pending) immutable object.

Mirrors ``python/ray/includes/object_ref.pxi`` in the reference: holds the
binary object ID, participates in local reference counting (handle count in
the owning process), and is serializable so refs can be passed as task
arguments or stored inside other objects (borrowing).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ray_tpu._private.ids import ObjectID, TaskID

if TYPE_CHECKING:
    pass


class ObjectRef:
    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: ObjectID, _register: bool = True):
        self._id = object_id
        self._owned = False
        if _register:
            from ray_tpu._private import worker as _worker_mod

            w = _worker_mod.global_worker_or_none()
            if w is not None:
                w.register_object_ref(self)
                self._owned = True

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> TaskID:
        return self._id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        from ray_tpu._private import worker as _worker_mod

        fut: concurrent.futures.Future = concurrent.futures.Future()
        w = _worker_mod.global_worker()

        def _on_ready(_oid):
            ready, value, error = w.memory_store.peek(self._id)
            assert ready
            if error is not None:
                fut.set_exception(error)
            else:
                fut.set_result(value)

        w.memory_store.on_ready(self._id, _on_ready)
        return fut

    def as_future(self, loop=None):
        """Return an ``asyncio.Future`` on ``loop`` (default: the running
        loop) resolving to the value. Unlike :meth:`future` +
        ``asyncio.wrap_future`` this is one cross-thread hop
        (``call_soon_threadsafe``) per completion, which matters on the
        event-loop ingress hot path. Task failures resolve to the
        user-level exception, matching ``ray_tpu.get``."""
        import asyncio

        from ray_tpu import exceptions as _exc
        from ray_tpu._private import worker as _worker_mod

        if loop is None:
            loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()
        w = _worker_mod.global_worker()

        def _on_ready(_oid):
            ready, value, error = w.memory_store.peek(self._id)
            assert ready
            if isinstance(error, _exc.TaskError):
                error = error.as_instanceof_cause()

            def _set():
                if fut.cancelled():
                    return
                if error is not None:
                    fut.set_exception(error)
                else:
                    fut.set_result(value)

            try:
                loop.call_soon_threadsafe(_set)
            except RuntimeError:
                # Loop closed (e.g. proxy shutdown mid-request): the
                # future's consumer is gone; do not break the store's
                # callback chain for other waiters.
                pass

        w.memory_store.on_ready(self._id, _on_ready)
        return fut

    def __await__(self):
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return asyncio.wrap_future(self.future()).__await__()
        return self.as_future().__await__()

    def __hash__(self) -> int:
        return hash(self._id)

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        # Deserialization re-registers the handle with the local worker,
        # which is how borrowed refs enter the local refcount.
        return (ObjectRef, (self._id,))

    def __del__(self):
        if self._owned:
            try:
                from ray_tpu._private import worker as _worker_mod

                w = _worker_mod.global_worker_or_none()
                if w is not None:
                    w.unregister_object_ref(self._id)
            except Exception:  # interpreter shutdown
                pass


class ObjectRefGenerator:
    """The value of a ``num_returns="dynamic"`` task: an iterable of the
    ObjectRefs created for the task's yielded outputs (reference:
    ``ray._raylet.ObjectRefGenerator``). Holding the generator (or any
    ref from it) keeps the corresponding objects alive."""

    __slots__ = ("_refs",)

    def __init__(self, refs):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self) -> int:
        return len(self._refs)

    def __getitem__(self, i) -> "ObjectRef":
        return self._refs[i]

    def __repr__(self) -> str:
        return f"ObjectRefGenerator({len(self._refs)} refs)"
