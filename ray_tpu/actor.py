"""Actor classes and handles.

Reference: ``python/ray/actor.py`` — ``@remote`` on a class yields an
``ActorClass``; ``.remote(...)`` submits an actor-creation task and returns
an ``ActorHandle`` whose method accessors submit ordered actor tasks.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.resources import normalize_request
from ray_tpu._private.task_spec import (check_isolate_process,
                                        get_ambient_trace_parent,
                                        intern_template,
                                        job_id_for_submit,
                                        trace_parent_from,
                                        DefaultSchedulingStrategy,
                                        TaskKind)

_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "memory", "resources", "name",
    "namespace", "lifetime", "max_restarts", "max_task_retries",
    "max_concurrency", "max_pending_calls", "scheduling_strategy",
    "runtime_env", "get_if_exists", "_metadata", "isolate_process",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use handle.{self._method_name}.remote()."
        )

    def options(self, num_returns: Optional[int] = None, name: str = "",
                **_ignored) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            num_returns if num_returns is not None else self._num_returns,
        )

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._num_returns
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls: type, actor_name: Optional[str],
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._cls = cls
        self._actor_name = actor_name
        self._max_task_retries = max_task_retries
        self._seq_lock = threading.Lock()
        self._seq = 0
        # (method_name, num_returns) -> interned SpecTemplate: method
        # calls pay only per-call fields (args, seq, trace) after the
        # first submission through this handle.
        self._method_templates: dict = {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if not hasattr(self._cls, name):
            raise AttributeError(
                f"Actor class {self._cls.__name__!r} has no method {name!r}"
            )
        return ActorMethod(self, name)

    def _submit_method(self, method_name, args, kwargs, num_returns):
        w = worker_mod.global_worker()
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        key = (method_name, num_returns)
        tpl = self._method_templates.get(key)
        if tpl is None:
            tpl = intern_template(
                kind=TaskKind.ACTOR_TASK,
                func=method_name,
                name=f"{self._cls.__name__}.{method_name}",
                num_returns=num_returns,
                resources={},
                max_retries=self._max_task_retries,
            )
            self._method_templates[key] = tpl
        _ctx = w.task_context.current()
        _ctx_spec = _ctx["task_spec"] if _ctx else None
        spec = tpl.make_spec(
            TaskID.from_random(), args, kwargs,
            actor_id=self._actor_id,
            sequence_number=seq,
            trace_parent=(trace_parent_from(_ctx_spec)
                          if _ctx else get_ambient_trace_parent()),
            job_id=job_id_for_submit(_ctx_spec),
        )
        refs = w.submit(spec)
        # dynamic: the single ref resolves to an ObjectRefGenerator
        return refs[0] if num_returns in (1, "dynamic") else refs

    def __repr__(self):
        return f"ActorHandle({self._cls.__name__}, {self._actor_id.hex()[:8]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._cls, self._actor_name, self._max_task_retries),
        )


class ActorClass:
    def __init__(self, cls: type, **default_options):
        bad = set(default_options) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options for an actor: {sorted(bad)}")
        self._cls = cls
        self._default_options = default_options
        self._template = None  # interned creation-spec slice (first .remote())
        self.__name__ = cls.__name__

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **options) -> "ActorClass":
        bad = set(options) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"Invalid options: {sorted(bad)}")
        return ActorClass(self._cls, **{**self._default_options, **options})

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._default_options
        w = worker_mod.global_worker()
        name = opts.get("name")
        namespace = opts.get("namespace")
        if opts.get("get_if_exists") and name:
            try:
                return w.gcs.get_named_actor(name, namespace)
            except ValueError:
                pass
        tpl = self._template
        if tpl is None:
            # Actors default to 0 CPU for lifetime (1 CPU only during
            # creation in the reference; we hold the declared request for
            # the lifetime).
            resources = normalize_request(
                num_cpus=opts.get("num_cpus"),
                num_tpus=opts.get("num_tpus"),
                num_gpus=opts.get("num_gpus"),
                memory=opts.get("memory"),
                resources=opts.get("resources"),
                default_cpus=0.0,
            )
            strategy = opts.get("scheduling_strategy") or \
                DefaultSchedulingStrategy()
            tpl = self._template = intern_template(
                kind=TaskKind.ACTOR_CREATION,
                func=self._cls,
                name=f"{self._cls.__name__}.__init__",
                num_returns=1,
                resources=resources,
                max_restarts=opts.get("max_restarts", 0),
                max_task_retries=opts.get("max_task_retries", 0),
                max_concurrency=opts.get("max_concurrency", 1),
                actor_name=name,
                namespace=namespace,
                lifetime=opts.get("lifetime"),
                max_pending_calls=opts.get("max_pending_calls", -1),
                scheduling_strategy=strategy,
                runtime_env=opts.get("runtime_env"),
                isolate_process=check_isolate_process(
                    opts.get("isolate_process", False)),
            )
        actor_id = ActorID.from_random()
        _ctx = w.task_context.current()
        _ctx_spec = _ctx["task_spec"] if _ctx else None
        spec = tpl.make_spec(
            TaskID.from_random(), args, kwargs,
            actor_id=actor_id,
            trace_parent=(trace_parent_from(_ctx_spec)
                          if _ctx else get_ambient_trace_parent()),
            job_id=job_id_for_submit(_ctx_spec),
        )
        handle = ActorHandle(
            actor_id, self._cls, name, opts.get("max_task_retries", 0)
        )
        if name:
            w.gcs.register_named_actor(name, namespace, handle)
        w.submit(spec)
        return handle


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor (reference: ``ray.get_actor``)."""
    return worker_mod.global_worker().gcs.get_named_actor(name, namespace)
