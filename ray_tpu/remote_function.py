"""``@remote`` functions.

Reference: ``python/ray/remote_function.py`` — a decorated function becomes a
handle whose ``.remote(...)`` submits a TaskSpec and returns ObjectRef(s);
``.options(...)`` overrides per-call options.
"""

from __future__ import annotations

import functools

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import TaskID
from ray_tpu._private.resources import normalize_request
from ray_tpu._private.task_spec import (
    check_isolate_process,
    get_ambient_trace_parent,
    intern_template,
    job_id_for_submit,
    trace_parent_from,
    DefaultSchedulingStrategy,
    QueuedTaskHeader,
    SchedulingStrategy,
    TaskKind,
)

_TASK_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "memory", "resources", "num_returns",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "runtime_env", "_metadata", "isolate_process",
}


# fid -> the exact cloudpickle whose sha1 is the fid (the function-
# distribution cache's export source; one entry per unique definition).
_EXPORT_BLOBS: dict = {}  # raylint: disable=R7 -- the function-cache export source: one entry per unique function DEFINITION (sha1-keyed), and a late-joining node may fetch any still-referenced fid at any time, so eviction here would break cluster-wide function resolution; bounded by the program's distinct remote definitions


def get_export_blob(fid: bytes):
    return _EXPORT_BLOBS.get(fid)


class RemoteFunction:
    def __init__(self, func, **default_options):
        bad = set(default_options) - _TASK_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options for a function: {sorted(bad)}")
        self._function = func
        self._default_options = default_options
        # Export-cache identity, computed lazily at first .remote():
        # hash of the cloudpickled definition. NB this freezes the
        # function's captured state at first submission (the reference's
        # one-time function export does the same); module-level
        # functions are unaffected (pickled by reference).
        self._func_id: bytes | None = None
        # Interned invariant spec slice, built at first .remote():
        # subsequent submits pay only per-call fields (task id, args,
        # trace context) — the serialize-once TaskSpec idea of the
        # reference core worker, applied in-process.
        self._template = None
        functools.update_wrapper(self, func)

    def _export_id(self):
        if self._func_id is None:
            import hashlib

            import cloudpickle

            try:
                blob = cloudpickle.dumps(self._function)
            except Exception:
                # Unpicklable closure (lock, socket, ...): fine in
                # local mode where the function is called in-process —
                # no export id, everything ships/runs inline as before.
                self._func_id = False
                return None
            self._func_id = hashlib.sha1(blob).digest()
            # The blob whose hash IS the id is what any export must
            # store — re-pickling later could capture mutated closure
            # state under the same id (divergent versions per node).
            _EXPORT_BLOBS[self._func_id] = blob
        return self._func_id or None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__name__!r} cannot be called "
            f"directly; use {self._function.__name__}.remote()."
        )

    def options(self, **options) -> "RemoteFunction":
        bad = set(options) - _TASK_OPTIONS
        if bad:
            raise ValueError(f"Invalid options: {sorted(bad)}")
        merged = {**self._default_options, **options}
        rf = RemoteFunction(self._function, **merged)
        rf._func_id = self._func_id  # same definition: share the export
        return rf

    def _build_template(self):
        opts = self._default_options
        resources = normalize_request(
            num_cpus=opts.get("num_cpus"),
            num_tpus=opts.get("num_tpus"),
            num_gpus=opts.get("num_gpus"),
            memory=opts.get("memory"),
            resources=opts.get("resources"),
            default_cpus=1.0,
        )
        strategy = opts.get("scheduling_strategy") or DefaultSchedulingStrategy()
        if not isinstance(strategy, SchedulingStrategy):
            raise TypeError(
                f"scheduling_strategy must be a SchedulingStrategy, got {strategy!r}"
            )
        return intern_template(
            kind=TaskKind.NORMAL_TASK,
            func=self._function,
            name=opts.get("name") or self._function.__qualname__,
            num_returns=opts.get("num_returns", 1),
            resources=resources,
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=opts.get("retry_exceptions", False),
            scheduling_strategy=strategy,
            runtime_env=opts.get("runtime_env"),
            isolate_process=check_isolate_process(opts.get("isolate_process", False)),
            func_id=self._export_id(),
        )

    def remote(self, *args, **kwargs):
        w = worker_mod.global_worker()
        tpl = self._template
        if tpl is None:
            tpl = self._template = self._build_template()
        ctx = w.task_context.current()
        ctx_spec = ctx["task_spec"] if ctx else None
        use_header = ray_config.sched_compact_queue and \
            type(tpl.scheduling_strategy) is \
            DefaultSchedulingStrategy and \
            getattr(w, "supports_compact_submit", False)
        if use_header:
            # Compact queued representation: submit a header (template
            # reference + per-call fields) instead of a full TaskSpec —
            # the scheduler materializes the spec only at dispatch, so
            # a deep backlog holds header bytes, not spec bytes. Minting
            # a header plus the proto-based materialization is CHEAPER
            # than one make_spec (perf_bench --ab-sched), so immediate
            # dispatches take this path too.
            spec = QueuedTaskHeader(
                tpl, TaskID.from_random(), args, kwargs,
                depth=(ctx_spec.depth + 1) if ctx else 0,
                trace_parent=(trace_parent_from(ctx_spec)
                              if ctx else get_ambient_trace_parent()),
                job_id=job_id_for_submit(ctx_spec),
            )
        else:
            spec = tpl.make_spec(
                TaskID.from_random(), args, kwargs,
                depth=(ctx_spec.depth + 1) if ctx else 0,
                trace_parent=(trace_parent_from(ctx_spec)
                              if ctx else get_ambient_trace_parent()),
                job_id=job_id_for_submit(ctx_spec),
            )
        refs = w.submit(spec)
        num_returns = tpl.num_returns
        if num_returns == 0:
            return None
        if num_returns == 1 or num_returns == "dynamic":
            return refs[0]  # dynamic: the ObjectRefGenerator's ref
        return refs


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(**options)`` decorator for functions and classes.

    Reference: ``ray.remote`` (``python/ray/_private/worker.py:2871``).
    """
    from ray_tpu.actor import ActorClass

    def _make(obj, options):
        if isinstance(obj, type):
            return ActorClass(obj, **options)
        if callable(obj):
            return RemoteFunction(obj, **options)
        raise TypeError(f"@remote requires a function or class, got {type(obj)}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return _make(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")

    def decorator(obj):
        return _make(obj, kwargs)

    return decorator
