"""Searcher ABC + wrappers.

Reference: `python/ray/tune/search/searcher.py` (Searcher),
`concurrency_limiter.py`, `repeater.py`. Custom searchers implement
`suggest`/`on_trial_complete`; the runner interleaves suggestions with
completions. An Optuna adapter is provided when optuna is installed.
"""

from __future__ import annotations

import random as _random
from typing import Any, Dict, Optional



class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass

    def set_search_properties(self, metric, mode, config) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True


class RandomSearch(Searcher):
    """Samples from the space independently per suggestion."""

    def __init__(self, space: Dict[str, Any], seed=None, **kwargs):
        super().__init__(**kwargs)
        self.space = space
        self._rng = _random.Random(seed)

    def suggest(self, trial_id: str):
        from ray_tpu.tune.search.basic_variant import _sample_leaves

        return _sample_leaves(self.space, self._rng)


class ConcurrencyLimiter(Searcher):
    def __init__(self, searcher: Searcher, max_concurrent: int = 8):
        super().__init__(metric=searcher.metric, mode=searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None  # runner retries later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class Repeater(Searcher):
    """Repeat each suggestion N times and average the metric."""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        super().__init__(metric=searcher.metric, mode=searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self._group_of: Dict[str, str] = {}
        self._configs: Dict[str, dict] = {}
        self._counts: Dict[str, int] = {}
        self._scores: Dict[str, list] = {}

    def suggest(self, trial_id: str):
        # Find a group needing more repeats, else open a new one.
        for gid, count in self._counts.items():
            if count < self.repeat:
                self._counts[gid] += 1
                self._group_of[trial_id] = gid
                return dict(self._configs[gid])
        cfg = self.searcher.suggest(trial_id)
        if cfg is None:
            return None
        gid = trial_id
        self._configs[gid] = cfg
        self._counts[gid] = 1
        self._scores[gid] = []
        self._group_of[trial_id] = gid
        return dict(cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        gid = self._group_of.get(trial_id)
        if gid is None:
            return
        if result and self.metric and self.metric in result:
            self._scores[gid].append(result[self.metric])
        if len(self._scores[gid]) >= self.repeat:
            avg = sum(self._scores[gid]) / len(self._scores[gid])
            self.searcher.on_trial_complete(
                gid, {self.metric: avg} if self.metric else None, error)


class OptunaSearch(Searcher):
    """Adapter over optuna's TPE (available only if optuna is installed)."""

    def __init__(self, space: Dict[str, Any], metric: str,
                 mode: str = "max", seed=None):
        super().__init__(metric=metric, mode=mode)
        import optuna  # noqa: F401 - raises if unavailable

        self._optuna = optuna
        sampler = optuna.samplers.TPESampler(seed=seed)
        direction = "maximize" if mode == "max" else "minimize"
        self._study = optuna.create_study(direction=direction,
                                          sampler=sampler)
        self._space = space
        self._trials: Dict[str, Any] = {}

    def suggest(self, trial_id: str):
        ot = self._study.ask()
        self._trials[trial_id] = ot
        from ray_tpu.tune.search import sample as S

        cfg = {}
        for k, v in self._space.items():
            if isinstance(v, S.Uniform):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper)
            elif isinstance(v, S.LogUniform):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=True)
            elif isinstance(v, S.RandInt):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1)
            elif isinstance(v, S.Choice):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, S.Domain):
                cfg[k] = v.sample(_random.Random())
            else:
                cfg[k] = v
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])
