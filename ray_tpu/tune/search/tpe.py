"""Native Tree-structured Parzen Estimator searcher.

Reference role: the HyperOpt wrapper (`tune/search/hyperopt/`) — the
hyperopt package is absent from this image, so the TPE algorithm
(Bergstra et al. 2011) is implemented directly: completed trials split
into a good quantile l(x) and the rest g(x); each is modeled per
dimension with a kernel density (Gaussians over normalized continuous
values, smoothed counts over categories); candidates sampled from l(x)
are scored by the acquisition l(x)/g(x) and the best is suggested.
"""

from __future__ import annotations

import math
import random as _random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import sample as S
from ray_tpu.tune.search.searcher import Searcher


class TPESearch(Searcher):
    # Class-level default so searchers unpickled from pre-telemetry
    # experiment state resume without AttributeError.
    model_suggestions = 0

    def __init__(self, space: Dict[str, Any], metric: str,
                 mode: str = "max", *, n_startup: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed=None):
        super().__init__(metric=metric, mode=mode)
        self.space = space
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = _random.Random(seed)
        self._observations: List[Tuple[Dict[str, Any], float]] = []
        self._pending: Dict[str, Dict[str, Any]] = {}
        # Telemetry: how many suggestions came from the fitted model vs
        # random startup (tests assert the model phase actually runs —
        # an eagerly-suggesting driver would silently reduce TPE to
        # random search).
        self.model_suggestions = 0

    # -- dimension helpers ----------------------------------------------

    def _dims(self):
        # Numeric spec: (lower, upper, log, q, exclusive_upper) —
        # RandInt/QRandInt sample with an EXCLUSIVE upper (randrange
        # semantics), and Q-domains snap to multiples of q; TPE-phase
        # candidates must respect both or they leave the domain the
        # startup phase defined.
        for key, dom in self.space.items():
            if isinstance(dom, S.QUniform):
                yield key, "float", (dom.lower, dom.upper, False,
                                     dom.q, False)
            elif isinstance(dom, S.Uniform):
                yield key, "float", (dom.lower, dom.upper, False,
                                     None, False)
            elif isinstance(dom, S.LogUniform):
                yield key, "float", (dom.lower, dom.upper, True,
                                     None, False)
            elif isinstance(dom, S.QRandInt):
                yield key, "int", (dom.lower, dom.upper, False,
                                   dom.q, True)
            elif isinstance(dom, S.RandInt):
                yield key, "int", (dom.lower, dom.upper, False,
                                   None, True)
            elif isinstance(dom, S.Choice):
                yield key, "cat", tuple(dom.categories)
            elif isinstance(dom, S.Domain):
                yield key, "domain", dom
            else:
                yield key, "const", dom

    @staticmethod
    def _norm(v, lo, hi, log):
        if log:
            lo, hi, v = math.log(lo), math.log(hi), math.log(max(v, 1e-300))
        return (v - lo) / max(hi - lo, 1e-12)

    @staticmethod
    def _denorm(u, lo, hi, log):
        if log:
            return math.exp(math.log(lo) + u * (math.log(hi)
                                                - math.log(lo)))
        return lo + u * (hi - lo)

    # -- TPE core --------------------------------------------------------

    @staticmethod
    def _rank_split(obs, gamma):
        ranked = sorted(obs, key=lambda p: -p[1])
        k = max(1, int(len(ranked) * gamma))
        return ranked[:k], ranked[k:]

    def _split(self):
        return self._rank_split(self._observations, self.gamma)

    def _kde_sample(self, points: List[float]) -> float:
        # Parzen window: pick an observed point, jitter by its bandwidth.
        bw = max(0.1, 1.0 / max(1, len(points)) ** 0.5 * 0.5)
        center = self._rng.choice(points) if points \
            else self._rng.random()
        return min(1.0, max(0.0, self._rng.gauss(center, bw)))

    @staticmethod
    def _kde_logpdf(x: float, points: List[float]) -> float:
        if not points:
            return 0.0
        bw = max(0.1, 1.0 / len(points) ** 0.5 * 0.5)
        acc = 0.0
        for c in points:
            acc += math.exp(-0.5 * ((x - c) / bw) ** 2)
        return math.log(acc / (len(points) * bw) + 1e-12)

    def _cat_logp(self, value, configs: List[dict], key, cats) -> float:
        counts = {c: 1.0 for c in cats}  # +1 smoothing
        for cfg in configs:
            if cfg.get(key) in counts:
                counts[cfg.get(key)] += 1.0
        total = sum(counts.values())
        return math.log(counts.get(value, 1.0) / total)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._observations) < self.n_startup:
            cfg = {k: (dom.sample(self._rng)
                       if isinstance(dom, S.Domain) else dom)
                   for k, dom in self.space.items()}
            self._pending[trial_id] = cfg
            return dict(cfg)
        self.model_suggestions += 1
        good, bad = self._split()
        good_cfgs = [c for c, _ in good]
        bad_cfgs = [c for c, _ in bad]
        best_cfg, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            cand: Dict[str, Any] = {}
            score = 0.0
            for key, kind, spec in self._dims():
                if kind in ("float", "int"):
                    lo, hi, log, q, excl = spec
                    pts_g = [self._norm(c[key], lo, hi, log)
                             for c in good_cfgs if key in c]
                    pts_b = [self._norm(c[key], lo, hi, log)
                             for c in bad_cfgs if key in c]
                    u = self._kde_sample(pts_g)
                    score += self._kde_logpdf(u, pts_g) \
                        - self._kde_logpdf(u, pts_b)
                    v = self._denorm(u, lo, hi, log)
                    if kind == "int":
                        top = hi - 1 if excl else hi
                        v = int(min(max(round(v), lo), top))
                        if q:  # floor to the grid, matching QRandInt
                            v = max((v // int(q)) * int(q), int(lo))
                    else:
                        v = min(max(v, lo), hi)
                        if q:
                            v = min(max(round(v / q) * q, lo), hi)
                    cand[key] = v
                elif kind == "cat":
                    cats = spec
                    # sample from l(x)'s smoothed categorical
                    weights = []
                    for c in cats:
                        weights.append(math.exp(self._cat_logp(
                            c, good_cfgs, key, cats)))
                    total = sum(weights)
                    r = self._rng.random() * total
                    acc = 0.0
                    value = cats[-1]
                    for c, w in zip(cats, weights):
                        acc += w
                        if r <= acc:
                            value = c
                            break
                    score += self._cat_logp(value, good_cfgs, key, cats) \
                        - self._cat_logp(value, bad_cfgs, key, cats)
                    cand[key] = value
                elif kind == "domain":
                    cand[key] = spec.sample(self._rng)
                else:
                    cand[key] = spec
            if score > best_score:
                best_cfg, best_score = cand, score
        self._pending[trial_id] = best_cfg
        return dict(best_cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or error or not result or \
                self.metric not in result:
            return
        value = result[self.metric]
        self._observations.append(
            (cfg, value if self.mode == "max" else -value))


class BOHBSearch(TPESearch):
    """BOHB's model half (reference `tune/search/bohb/` TuneBOHB,
    Falkner et al. 2018): TPE fit on results at the LARGEST budget that
    has enough observations, so cheap low-rung evaluations guide early
    sampling and high-rung results take over as they accumulate. Pair
    with `HyperBandScheduler` (the bracket half); report intermediate
    results via on_trial_result so rung-level observations land even
    for trials the scheduler stops early.
    """

    def __init__(self, space, metric, mode: str = "max", *,
                 time_attr: str = "training_iteration",
                 min_points_per_budget: Optional[int] = None, **kwargs):
        super().__init__(space, metric, mode, **kwargs)
        self.time_attr = time_attr
        self.min_points = min_points_per_budget \
            if min_points_per_budget is not None \
            else len(list(self._dims())) + 1
        # budget -> [(config, signed score)]
        self._by_budget: Dict[float, List[Tuple[Dict[str, Any],
                                                float]]] = {}

    def on_trial_result(self, trial_id, result):
        cfg = self._pending.get(trial_id)
        metric = result.get(self.metric)
        budget = result.get(self.time_attr)
        if cfg is None or metric is None or budget is None:
            return
        score = metric if self.mode == "max" else -metric
        self._by_budget.setdefault(float(budget), []).append(
            (dict(cfg), score))

    def _split(self):
        # Largest budget with enough data wins (the BOHB rule); fall
        # back through smaller budgets, then the terminal-result pool.
        for budget in sorted(self._by_budget, reverse=True):
            obs = self._by_budget[budget]
            if len(obs) >= self.min_points:
                return self._rank_split(obs, self.gamma)
        return super()._split()

    def on_trial_complete(self, trial_id, result=None, error=False):
        super().on_trial_complete(trial_id, result, error)
        # Bound per-budget history like the observation pool.
        for budget in list(self._by_budget):
            if len(self._by_budget[budget]) > 500:
                self._by_budget[budget] = self._by_budget[budget][-500:]
