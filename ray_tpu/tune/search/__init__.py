"""Search algorithms (reference `python/ray/tune/search/`)."""

from ray_tpu.tune.search.sample import (  # noqa: F401
    Choice,
    Domain,
    GridSearch,
    choice,
    grid_search,
    loguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.search.basic_variant import (  # noqa: F401
    BasicVariantGenerator,
)
from ray_tpu.tune.search.searcher import (  # noqa: F401
    ConcurrencyLimiter,
    OptunaSearch,
    RandomSearch,
    Repeater,
    Searcher,
)
from ray_tpu.tune.search.tpe import (  # noqa: F401
    BOHBSearch,
    TPESearch,
)
