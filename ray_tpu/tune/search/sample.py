"""Search-space sampling DSL.

Reference: `python/ray/tune/search/sample.py` — `uniform`, `loguniform`,
`randint`, `choice`, `grid_search`, `qrandint`, `randn`, plus `.sample()`
semantics used by the variant generator.
"""

from __future__ import annotations

import random as _random
from typing import Any, Dict, Sequence


class Domain:
    def sample(self, rng: _random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float, base: float = 10.0):
        import math

        self.lower, self.upper, self.base = lower, upper, base
        self._lo = math.log(lower, base)
        self._hi = math.log(upper, base)

    def sample(self, rng):
        return self.base ** rng.uniform(self._lo, self._hi)


class QUniform(Domain):
    def __init__(self, lower: float, upper: float, q: float):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(v / self.q) * self.q


class RandInt(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class QRandInt(Domain):
    def __init__(self, lower: int, upper: int, q: int):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.randrange(self.lower, self.upper)
        return (v // self.q) * self.q


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Randn(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class GridSearch:
    """Marker resolved by the variant generator (cartesian product)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float, base: float = 10.0) -> LogUniform:
    return LogUniform(lower, upper, base)


def quniform(lower: float, upper: float, q: float) -> QUniform:
    return QUniform(lower, upper, q)


def randint(lower: int, upper: int) -> RandInt:
    return RandInt(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> QRandInt:
    return QRandInt(lower, upper, q)


def choice(categories: Sequence[Any]) -> Choice:
    return Choice(categories)


def randn(mean: float = 0.0, sd: float = 1.0) -> Randn:
    return Randn(mean, sd)


def grid_search(values: Sequence[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def sample_from(fn) -> "Function":
    return Function(fn)


class Function(Domain):
    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):
        try:
            return self.fn({})
        except TypeError:
            return self.fn()
