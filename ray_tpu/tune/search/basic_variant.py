"""Grid + random variant generation.

Reference: `python/ray/tune/search/basic_variant.py` +
`variant_generator.py` — expand `grid_search` entries into a cartesian
product, sample `Domain` leaves per variant, repeat `num_samples` times.
"""

from __future__ import annotations

import itertools
import random as _random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.search.sample import Domain


def _find_grid_axes(space: Dict[str, Any], prefix=()) -> List[Tuple[tuple, list]]:
    axes = []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            if set(v.keys()) == {"grid_search"}:
                axes.append((path, v["grid_search"]))
            else:
                axes.extend(_find_grid_axes(v, path))
    return axes


def _set_path(cfg: dict, path: tuple, value):
    d = cfg
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _sample_leaves(space, rng):
    if isinstance(space, Domain):
        return space.sample(rng)
    if isinstance(space, dict):
        return {k: _sample_leaves(v, rng) for k, v in space.items()}
    if isinstance(space, (list, tuple)):
        return type(space)(_sample_leaves(v, rng) for v in space)
    return space


def generate_variants(space: Dict[str, Any], num_samples: int = 1,
                      seed: int = None) -> Iterator[Dict[str, Any]]:
    rng = _random.Random(seed)
    grid_axes = _find_grid_axes(space)
    if grid_axes:
        paths, values = zip(*grid_axes)
        combos = list(itertools.product(*values))
    else:
        paths, combos = (), [()]
    for _ in range(num_samples):
        for combo in combos:
            cfg = _sample_leaves(space, rng)
            for path, value in zip(paths, combo):
                _set_path(cfg, path, value)
            yield cfg


class BasicVariantGenerator:
    def __init__(self, max_concurrent: int = 0):
        self.max_concurrent = max_concurrent

    def generate(self, space: Dict[str, Any],
                 num_samples: int = 1, seed=None) -> List[Dict[str, Any]]:
        return list(generate_variants(space, num_samples, seed))
