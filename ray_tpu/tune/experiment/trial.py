"""Trial: one configuration's lifecycle.

Reference: `python/ray/tune/experiment/trial.py` — status FSM
(PENDING/RUNNING/PAUSED/TERMINATED/ERROR), per-trial checkpoint manager,
and result history.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.checkpoint_manager import CheckpointManager
from ray_tpu.air.config import CheckpointConfig


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(self, config: Dict[str, Any],
                 checkpoint_config: Optional[CheckpointConfig] = None,
                 trial_id: Optional[str] = None, name: str = ""):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.name = name or f"trial_{self.trial_id}"
        self.config = config
        self.status = Trial.PENDING
        self.results: List[Dict[str, Any]] = []
        self.last_result: Dict[str, Any] = {}
        self.error: Optional[Exception] = None
        self.error_tb: Optional[str] = None
        self.num_failures = 0
        self.checkpoint_manager = CheckpointManager(checkpoint_config)
        self.actor = None  # runner-owned
        self.metric_history: Dict[str, List[float]] = {}
        # Per-trial resource override (ResourceChangingScheduler); None
        # falls back to the runner-wide resources_per_trial.
        self.resources: Optional[Dict[str, float]] = None

    @property
    def checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint_manager.latest

    def record_result(self, result: Dict[str, Any]):
        self.results.append(result)
        self.last_result = result
        for k, v in result.items():
            if isinstance(v, (int, float)):
                self.metric_history.setdefault(k, []).append(float(v))

    def is_finished(self) -> bool:
        return self.status in (Trial.TERMINATED, Trial.ERROR)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"
