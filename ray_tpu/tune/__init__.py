"""ray_tpu.tune: hyperparameter optimization + the Train execution
substrate (reference `python/ray/tune/`, SURVEY.md §2.4).

Function API: `tune.report` is `air.session.report`; Trainables run as
actors under the TrialRunner event loop with schedulers (ASHA, PBT,
median-stopping), searchers (grid/random/Optuna), stoppers, and
checkpoint-based retry/clone.
"""

from ray_tpu.air import session as _session
from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.tune.result_grid import ExperimentAnalysis, ResultGrid  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.stopper import (  # noqa: F401
    CombinedStopper,
    ExperimentPlateauStopper,
    FunctionStopper,
    MaximumIterationStopper,
    Stopper,
    TrialPlateauStopper,
)
from ray_tpu.tune.trainable import (  # noqa: F401
    FunctionTrainable,
    Trainable,
    wrap_function,
)
from ray_tpu.tune.syncer import (  # noqa: F401
    LocalSyncer,
    SyncConfig,
    Syncer,
)
from ray_tpu.tune.tuner import Tuner, TuneConfig, run  # noqa: F401

# Function-API reporting (reference: `ray.tune.report` → air session).
report = _session.report
get_checkpoint = _session.get_checkpoint


def with_parameters(fn, **params):
    """Bind large constant objects to a trainable fn (reference:
    `tune.with_parameters` — passes via object store to avoid
    re-serialization per trial)."""
    import functools

    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in params.items()}

    if isinstance(fn, type):
        raise TypeError("with_parameters supports function trainables")

    @functools.wraps(fn)
    def wrapped(config):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        return fn(config, **resolved)

    return wrapped
