"""Trainable: the unit Tune schedules.

Reference: `python/ray/tune/trainable/trainable.py` (class API:
setup/step/save_checkpoint/load_checkpoint) and
`function_trainable.py` (function API: the user fn runs on a thread,
`session.report` rendezvous with `step()`). `wrap_trainer_as_trainable`
is the Train↔Tune bridge (`train/base_trainer.py:759` in the reference).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session as session_mod
from ray_tpu.air.checkpoint import Checkpoint

DONE = "done"


class Trainable:
    """Class API: subclass and implement setup/step/save/load."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = config or {}
        self.training_iteration = 0
        self._setup_done = False

    # -- subclass surface -------------------------------------------------

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Optional[Dict[str, Any]]:
        return None

    def load_checkpoint(self, checkpoint: Dict[str, Any]) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable supports in-place config reset
        (enables actor reuse in PBT)."""
        return False

    def cleanup(self) -> None:
        pass

    # -- framework surface ------------------------------------------------

    def train(self) -> Dict[str, Any]:
        if not self._setup_done:
            self.setup(self.config)
            self._setup_done = True
        result = self.step() or {}
        self.training_iteration += 1
        result.setdefault("training_iteration", self.training_iteration)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Optional[Checkpoint]:
        data = self.save_checkpoint()
        if data is None:
            return None
        return Checkpoint.from_dict({
            **data, "_iteration": self.training_iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        data = dict(checkpoint.to_dict())
        self.training_iteration = data.pop("_iteration", 0)
        if not self._setup_done:
            self.setup(self.config)
            self._setup_done = True
        self.load_checkpoint(data)

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps a user function; each `step()` returns the next
    `session.report` payload."""

    _fn: Callable = None  # bound by subclass factory

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        super().__init__(config)
        self._session: Optional[session_mod.TrainSession] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._tb: Optional[str] = None
        self._finished = threading.Event()
        self._restore_checkpoint: Optional[Checkpoint] = None
        self._last_checkpoint: Optional[Checkpoint] = None

    def setup(self, config: Dict[str, Any]) -> None:
        self._session = session_mod.TrainSession(
            checkpoint=self._restore_checkpoint)

        def run():
            session_mod.set_session(self._session)
            try:
                try:
                    self._fn(config)
                except TypeError as e:
                    if "positional argument" in str(e):
                        self._fn()
                    else:
                        raise
            except BaseException as e:  # noqa: BLE001
                self._error = e
                self._tb = traceback.format_exc()
            finally:
                session_mod.set_session(None)
                self._finished.set()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="tune-fn")
        self._thread.start()

    def step(self) -> Dict[str, Any]:
        while True:
            if not getattr(self, "_buffer", None):
                self._buffer = list(self._session.drain_results())
            if self._buffer:
                metrics, ckpt = self._buffer.pop(0)
                if ckpt is not None:
                    self._last_checkpoint = ckpt
                metrics = dict(metrics)
                metrics[DONE] = False
                return metrics
            if self._finished.is_set():
                if self._error is not None:
                    raise RuntimeError(
                        f"trainable function failed:\n{self._tb}")
                return {DONE: True}
            time.sleep(0.005)

    def save_checkpoint(self) -> Optional[Dict[str, Any]]:
        if self._last_checkpoint is None:
            return None
        return dict(self._last_checkpoint.to_dict())

    def restore(self, checkpoint: Checkpoint) -> None:
        # Function API restores by passing the checkpoint into the session
        # before the fn starts (reference semantics: session.get_checkpoint).
        data = dict(checkpoint.to_dict())
        self.training_iteration = data.pop("_iteration", 0)
        self._restore_checkpoint = Checkpoint.from_dict(data)
        self._last_checkpoint = self._restore_checkpoint

    def stop(self) -> None:
        self._finished.wait(timeout=1.0)
        self.cleanup()


def wrap_function(fn: Callable) -> type:
    """Function → Trainable subclass (reference: `wrap_function`,
    `tune/trainable/function_trainable.py`)."""

    class _Wrapped(FunctionTrainable):
        _fn = staticmethod(fn)

    _Wrapped.__name__ = getattr(fn, "__name__", "fn") + "_trainable"
    return _Wrapped


def wrap_trainer_as_trainable(trainer) -> type:
    """Train→Tune bridge: the trainer's `training_loop` becomes the
    trainable function; its own session.report calls stream results."""

    def _train_fn(config):
        if config:
            # Tune-sampled params override the trainer's loop config.
            if hasattr(trainer, "train_loop_config"):
                trainer.train_loop_config = {
                    **trainer.train_loop_config, **config}
        ckpt = session_mod.get_checkpoint()
        if ckpt is not None:
            trainer.resume_from_checkpoint = ckpt
        trainer.setup()
        trainer.training_loop()

    _train_fn.__name__ = type(trainer).__name__
    return wrap_function(_train_fn)
