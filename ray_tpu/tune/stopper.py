"""Stoppers (reference `python/ray/tune/stopper/`)."""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Dict


class Stopper:
    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self.max_iter = max_iter

    def __call__(self, trial_id, result):
        return result.get("training_iteration", 0) >= self.max_iter


class TrialPlateauStopper(Stopper):
    def __init__(self, metric: str, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4,
                 mode: str = "min"):
        self.metric = metric
        self.std = std
        self.num_results = num_results
        self.grace_period = grace_period
        self._window = defaultdict(lambda: deque(maxlen=num_results))
        self._iters = defaultdict(int)

    def __call__(self, trial_id, result):
        import numpy as np

        v = result.get(self.metric)
        self._iters[trial_id] += 1
        if v is None:
            return False
        w = self._window[trial_id]
        w.append(v)
        if self._iters[trial_id] < self.grace_period or \
                len(w) < self.num_results:
            return False
        return float(np.std(list(w))) < self.std


class ExperimentPlateauStopper(Stopper):
    def __init__(self, metric: str, std: float = 0.001, top: int = 10,
                 mode: str = "min", patience: int = 0):
        self.metric = metric
        self.std = std
        self.top = top
        self.mode = mode
        self.patience = patience
        self._best: list = []
        self._stale = 0

    def __call__(self, trial_id, result):
        import numpy as np

        v = result.get(self.metric)
        if v is None:
            return False
        self._best.append(v if self.mode == "max" else -v)
        self._best = sorted(self._best, reverse=True)[: self.top]
        if len(self._best) == self.top and \
                float(np.std(self._best)) < self.std:
            self._stale += 1
        else:
            self._stale = 0
        return False

    def stop_all(self):
        return self._stale > self.patience


class FunctionStopper(Stopper):
    def __init__(self, fn):
        self.fn = fn

    def __call__(self, trial_id, result):
        return self.fn(trial_id, result)


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self.stoppers = stoppers

    def __call__(self, trial_id, result):
        return any(s(trial_id, result) for s in self.stoppers)

    def stop_all(self):
        return any(s.stop_all() for s in self.stoppers)
