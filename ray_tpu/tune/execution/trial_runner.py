"""TrialRunner: the Tune event loop.

Reference: `python/ray/tune/execution/trial_runner.py:1140` (`step()` at
`:1315`) + `ray_trial_executor.py:185`. Trials run as actors
(`_TrainableActor` wrapping a Trainable); the runner starts pending trials
up to the concurrency cap, collects `train()` futures as they complete,
routes results through scheduler + stoppers, retries failures from the
last checkpoint (`FailureConfig.max_failures`), and supports PBT's
clone-and-perturb via `clone_trial`.
"""

from __future__ import annotations

import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.stopper import Stopper
from ray_tpu.tune.trainable import DONE, Trainable


@ray_tpu.remote
class _TrainableActor:
    def __init__(self, trainable_cls, config, checkpoint_data):
        self._inst: Trainable = trainable_cls(config)
        if checkpoint_data is not None:
            self._inst.restore(Checkpoint.from_dict(checkpoint_data))

    def train(self) -> Dict[str, Any]:
        return self._inst.train()

    def save(self) -> Optional[dict]:
        ckpt = self._inst.save()
        return None if ckpt is None else ckpt.to_dict()

    def restore(self, data: dict):
        self._inst.restore(Checkpoint.from_dict(data))
        return True

    def stop(self):
        self._inst.stop()
        return True


class TrialRunner:
    def __init__(self, trainable_cls, trials: List[Trial], *,
                 scheduler: Optional[TrialScheduler] = None,
                 stopper: Optional[Stopper] = None,
                 stop_criteria: Optional[Dict[str, Any]] = None,
                 failure_config: Optional[FailureConfig] = None,
                 max_concurrent_trials: Optional[int] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 callbacks: Optional[List] = None,
                 trial_generator: Optional[Any] = None,
                 generator_exhausted: Optional[Any] = None):
        self.trainable_cls = trainable_cls
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.stopper = stopper
        self.stop_criteria = stop_criteria or {}
        self.failure_config = failure_config or FailureConfig()
        self.max_concurrent = max_concurrent_trials or len(trials) or 1
        self.resources_per_trial = resources_per_trial or {"CPU": 1}
        self.callbacks = callbacks or []
        self._in_flight: Dict[Any, Trial] = {}
        self._stop_all = False
        # Lazy trial source (reference: SearchGenerator) — model-based
        # searchers must see completed results BEFORE suggesting later
        # configs; suggesting every trial up front would reduce them to
        # random search. The runner pulls a new trial whenever a
        # concurrency slot frees, until `generator_exhausted()`.
        self._trial_generator = trial_generator
        self._generator_exhausted = generator_exhausted or (lambda: True)

    # -- actor management ------------------------------------------------

    def _start_trial(self, trial: Trial,
                     checkpoint: Optional[Checkpoint] = None):
        res = dict(trial.resources or self.resources_per_trial)
        opts: Dict[str, Any] = {"num_cpus": res.pop("CPU", 1),
                                "max_restarts": 0}
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        ckpt_data = None
        src = checkpoint or trial.checkpoint
        if src is not None:
            ckpt_data = src.to_dict()
        trial.actor = _TrainableActor.options(**opts).remote(
            self.trainable_cls, trial.config, ckpt_data)
        trial.status = Trial.RUNNING
        for cb in self.callbacks:
            _safe(cb, "on_trial_start", trial=trial)

    def _stop_trial(self, trial: Trial, status: str):
        trial.status = status
        if trial.actor is not None:
            try:
                # Best-effort final checkpoint for restartable state.
                trial.actor.stop.remote()
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        for cb in self.callbacks:
            _safe(cb, "on_trial_complete", trial=trial)

    def _save_trial_checkpoint(self, trial: Trial):
        if trial.actor is None:
            return
        try:
            data = ray_tpu.get(trial.actor.save.remote(), timeout=30)
        except Exception:
            return
        if data is not None:
            trial.checkpoint_manager.register(
                Checkpoint.from_dict(data), trial.last_result)

    # -- PBT support -----------------------------------------------------

    def clone_trial(self, trial: Trial, donor: Trial,
                    new_config: Dict[str, Any]):
        """Replace `trial`'s state with donor's checkpoint + new config
        (PBT exploit/explore)."""
        self._save_checkpoint_from(donor)
        donor_ckpt = donor.checkpoint
        if donor_ckpt is None:
            return
        # Drop the running actor (its in-flight future is discarded when it
        # resolves — we mark the trial as restarting).
        for fut, t in list(self._in_flight.items()):
            if t is trial:
                del self._in_flight[fut]
        self._stop_trial(trial, Trial.PENDING)
        trial.config = new_config
        self._start_trial(trial, checkpoint=donor_ckpt)
        self._submit(trial)

    def update_trial_resources(self, trial: Trial,
                               resources: Dict[str, float]):
        """Checkpoint + restart `trial` with new resources
        (ResourceChangingScheduler's apply step — the reference likewise
        restarts from checkpoint; resources can't change under a live
        actor). Called from a scheduler's on_trial_result: the trial is
        left RUNNING and NOT resubmitted here — _handle_result's normal
        RUNNING branch issues the next train() (submitting here too
        would leave two concurrent futures training the trial at 2x)."""
        self._save_checkpoint_from(trial)
        for fut, t in list(self._in_flight.items()):
            if t is trial:
                del self._in_flight[fut]
        self._stop_trial(trial, Trial.PENDING)
        trial.resources = dict(resources)
        self._start_trial(trial, checkpoint=trial.checkpoint)

    def _save_checkpoint_from(self, donor: Trial):
        if donor.actor is not None:
            self._save_trial_checkpoint(donor)

    # -- event loop ------------------------------------------------------

    def _submit(self, trial: Trial):
        fut = trial.actor.train.remote()
        self._in_flight[fut] = trial

    def step(self):
        # Launch pending trials up to the cap.
        running = sum(1 for t in self.trials if t.status == Trial.RUNNING)
        for trial in self.trials:
            if running >= self.max_concurrent or self._stop_all:
                break
            if trial.status == Trial.PENDING:
                self._start_trial(trial)
                self._submit(trial)
                running += 1
        while (self._trial_generator is not None and not self._stop_all
               and running < self.max_concurrent
               and not self._generator_exhausted()):
            trial = self._trial_generator()
            if trial is None:
                # "Not now" (e.g. a ConcurrencyLimiter waiting on live
                # trials). If nothing is running or pending, nothing
                # will ever unblock it — drop the source (livelock
                # guard) rather than spin forever.
                if not any(t.status in (Trial.RUNNING, Trial.PENDING)
                           for t in self.trials):
                    self._trial_generator = None
                break
            self.trials.append(trial)
            self._start_trial(trial)
            self._submit(trial)
            running += 1
        if not self._in_flight:
            return
        ready, _ = ray_tpu.wait(list(self._in_flight), num_returns=1,
                                timeout=1.0)
        for fut in ready:
            trial = self._in_flight.pop(fut, None)
            if trial is None:
                continue
            try:
                result = ray_tpu.get(fut)
            except Exception as e:  # trial crashed
                self._handle_failure(trial, e)
                continue
            self._handle_result(trial, result)

    def _handle_result(self, trial: Trial, result: Dict[str, Any]):
        # A successful step clears transient-failure state.
        trial.error = None
        trial.error_tb = None
        if result.get(DONE):
            # Record final results that carry real metrics (class API);
            # skip the function API's bare completion sentinel.
            if set(result) - {DONE, "training_iteration"}:
                trial.record_result(result)
            self._save_trial_checkpoint(trial)
            self._stop_trial(trial, Trial.TERMINATED)
            self.scheduler.on_trial_complete(self, trial,
                                             trial.last_result)
            return
        trial.record_result(result)
        for cb in self.callbacks:
            _safe(cb, "on_trial_result", trial=trial, result=result)
        # Checkpoint bookkeeping: function trainables attach checkpoints
        # via session; class trainables save on frequency.
        self._save_trial_checkpoint(trial)
        if self._should_stop_by_criteria(result) or (
                self.stopper and self.stopper(trial.trial_id, result)):
            self._stop_trial(trial, Trial.TERMINATED)
            self.scheduler.on_trial_complete(self, trial, result)
        elif self.stopper and self.stopper.stop_all():
            self._stop_all = True
        else:
            decision = self.scheduler.on_trial_result(self, trial, result)
            if decision == TrialScheduler.STOP:
                self._stop_trial(trial, Trial.TERMINATED)
                self.scheduler.on_trial_complete(self, trial, result)
            elif trial.status == Trial.RUNNING:
                self._submit(trial)

    def _should_stop_by_criteria(self, result: Dict[str, Any]) -> bool:
        for k, v in self.stop_criteria.items():
            if k in result and result[k] >= v:
                return True
        return False

    def _handle_failure(self, trial: Trial, error: Exception):
        trial.num_failures += 1
        trial.error = error
        trial.error_tb = traceback.format_exc()
        max_failures = self.failure_config.max_failures
        if max_failures < 0 or trial.num_failures <= max_failures:
            # Retry from last checkpoint.
            self._stop_trial(trial, Trial.PENDING)
        else:
            self._stop_trial(trial, Trial.ERROR)
            self.scheduler.on_trial_complete(self, trial, None)
            if self.failure_config.fail_fast:
                self._stop_all = True

    def is_finished(self) -> bool:
        if self._stop_all:
            return True
        if self._trial_generator is not None and \
                not self._generator_exhausted():
            return False
        return all(t.is_finished() for t in self.trials)

    def run(self):
        try:
            while not self.is_finished():
                self.step()
        finally:
            for t in self.trials:
                if t.status == Trial.RUNNING:
                    self._stop_trial(
                        t, Trial.TERMINATED if self._stop_all
                        else Trial.ERROR)


def _safe(cb, method, **kwargs):
    fn = getattr(cb, method, None)
    if fn is None:
        return
    try:
        fn(**kwargs)
    except Exception:
        pass
