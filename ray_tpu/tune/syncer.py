"""Syncer: ship experiment/trial artifacts to durable storage.

Reference: `python/ray/tune/syncer.py` — a `SyncConfig` on the RunConfig
selects a `Syncer` that uploads the experiment directory (state file +
trial checkpoints) to an `upload_dir` after checkpoint events, rate-
limited by `sync_period`; `Tuner.restore` syncs back down first. The
reference speaks pyarrow.fs URIs (s3/gs); this environment has no object
store, so the built-ins are filesystem-to-filesystem (a network mount is
the multi-node story), and the ABC is the plug-in point for cloud
backends.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Optional, Union

logger = logging.getLogger(__name__)


class Syncer:
    """sync_up/sync_down move a whole directory tree; wait() blocks on
    any in-flight background transfer."""

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        raise NotImplementedError

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        raise NotImplementedError

    def delete(self, remote_dir: str) -> bool:
        shutil.rmtree(remote_dir, ignore_errors=True)
        return True

    def wait(self):
        pass


class LocalSyncer(Syncer):
    """Filesystem copy — the default. Tolerant of files vanishing
    mid-copy: event-triggered syncs run concurrently with atomic
    experiment-state saves (`*.tmp` + os.replace) and trial checkpoint
    writes, so individual files may disappear between scandir and copy.
    A skipped file is fine — the final forced sync (after writes
    quiesce) captures the complete tree."""

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        if not os.path.isdir(local_dir):
            return False
        for root, dirs, files in os.walk(local_dir):
            rel = os.path.relpath(root, local_dir)
            dst_root = os.path.join(remote_dir, rel) if rel != "." \
                else remote_dir
            os.makedirs(dst_root, exist_ok=True)
            for f in files:
                try:
                    shutil.copy2(os.path.join(root, f),
                                 os.path.join(dst_root, f))
                except FileNotFoundError:
                    continue  # vanished mid-copy (atomic replace)
        return True

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        if not os.path.isdir(remote_dir):
            return False
        shutil.copytree(remote_dir, local_dir, dirs_exist_ok=True)
        return True


class _BackgroundSyncer(Syncer):
    """Run another syncer's sync_up off-thread (the experiment loop never
    blocks on uploads — reference's default behavior)."""

    def __init__(self, inner: Syncer):
        self.inner = inner
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def _run(self, local_dir: str, remote_dir: str):
        try:
            self.inner.sync_up(local_dir, remote_dir)
        except BaseException as e:  # noqa: BLE001 — surfaced in wait()
            self._error = e

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        self.wait()
        self._thread = threading.Thread(
            target=self._run, args=(local_dir, remote_dir), daemon=True)
        self._thread.start()
        return True

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        self.wait()
        return self.inner.sync_down(remote_dir, local_dir)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError("background sync failed") from e


@dataclass
class SyncConfig:
    """Reference `tune/syncer.py` SyncConfig."""

    upload_dir: Optional[str] = None
    syncer: Union[str, Syncer, None] = "auto"  # "auto" | Syncer | None
    sync_period: float = 300.0
    sync_on_checkpoint: bool = True

    def resolve_syncer(self) -> Optional[Syncer]:
        if self.syncer is None or self.upload_dir is None:
            return None
        if isinstance(self.syncer, Syncer):
            return self.syncer
        if self.syncer == "auto":
            return _BackgroundSyncer(LocalSyncer())
        raise ValueError(f"unknown syncer {self.syncer!r}")


class SyncerCallback:
    """Tuner-side driver: rate-limited upload of the experiment dir."""

    def __init__(self, sync_config: SyncConfig, experiment_dir: str):
        self.config = sync_config
        self.experiment_dir = experiment_dir
        self.syncer = sync_config.resolve_syncer()
        self._last_sync = 0.0
        self.sync_errors = 0

    @property
    def remote_dir(self) -> Optional[str]:
        if self.config.upload_dir is None:
            return None
        return os.path.join(self.config.upload_dir,
                            os.path.basename(self.experiment_dir))

    def maybe_sync(self, *, force: bool = False,
                   on_checkpoint: bool = False):
        # Two independent triggers (reference SyncConfig semantics):
        # a checkpoint event syncs immediately iff sync_on_checkpoint,
        # while period-based syncing applies to every call regardless.
        if self.syncer is None:
            return
        now = time.monotonic()
        checkpoint_trigger = on_checkpoint and self.config.sync_on_checkpoint
        period_due = not self._last_sync or \
            now - self._last_sync >= self.config.sync_period
        if not force and not checkpoint_trigger and not period_due:
            return  # rate limit: full-tree copies are expensive
        self._last_sync = now
        try:
            self.syncer.sync_up(self.experiment_dir, self.remote_dir)
        except Exception:  # noqa: BLE001
            # One transient upload failure must not abort the experiment
            # loop; count it and keep training. With _BackgroundSyncer
            # the raise usually surfaces a PRIOR failed upload from its
            # internal wait() — retry once so a single stale error can't
            # also cancel this period's sync. close() still raises.
            self.sync_errors += 1
            logger.warning("background experiment sync failed "
                           "(%d so far); training continues",
                           self.sync_errors, exc_info=True)
            try:
                self.syncer.sync_up(self.experiment_dir, self.remote_dir)
            except Exception:  # noqa: BLE001
                self.sync_errors += 1
                logger.warning("experiment sync retry also failed",
                               exc_info=True)

    def close(self):
        # Final sync bypasses the error-swallowing periodic path: a
        # failure to persist the terminal experiment state must surface.
        if self.syncer is not None:
            # Drain any stale error from an earlier transient failure so
            # it can't abort the final upload of a now-healthy storage.
            try:
                self.syncer.wait()
            except Exception:  # noqa: BLE001
                self.sync_errors += 1
                logger.warning("stale background sync error drained at "
                               "close", exc_info=True)
            self.syncer.sync_up(self.experiment_dir, self.remote_dir)
            self.syncer.wait()


def sync_down_experiment(upload_dir: str, name: str,
                         local_dir: str) -> bool:
    """Fetch `<upload_dir>/<name>` into `<local_dir>/<name>` (the
    Tuner.restore entry point for synced experiments)."""
    syncer = LocalSyncer()
    return syncer.sync_down(os.path.join(upload_dir, name),
                            os.path.join(local_dir, name))
