"""Tuner: the HPO entry point.

Reference: `python/ray/tune/tuner.py` + `tune/impl/tuner_internal.py` +
`tune.run` (`tune/tune.py`). `Tuner(trainable, param_space=...).fit()`
expands the param space into trials, runs them through the TrialRunner,
and returns a ResultGrid.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.basic_variant import generate_variants
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.stopper import FunctionStopper, Stopper
from ray_tpu.tune.trainable import Trainable, wrap_function


@dataclass
class TuneConfig:
    """Reference: `tune/tune_config.py`."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1})


class Tuner:
    def __init__(self, trainable: Union[Callable, type], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"unsupported trainable: {trainable!r}")
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._trials: Optional[List[Trial]] = None

    def _make_trials(self) -> List[Trial]:
        tc = self.tune_config
        ckpt_cfg = self.run_config.checkpoint_config
        trials: List[Trial] = []
        if tc.search_alg is not None:
            tc.search_alg.set_search_properties(tc.metric, tc.mode,
                                                self.param_space)
            for i in range(tc.num_samples):
                tid = f"t{i:05d}"
                cfg = tc.search_alg.suggest(tid)
                if cfg is None:
                    break
                trials.append(Trial(cfg, checkpoint_config=ckpt_cfg,
                                    trial_id=tid))
        else:
            for i, cfg in enumerate(generate_variants(
                    self.param_space, tc.num_samples, tc.seed)):
                trials.append(Trial(cfg, checkpoint_config=ckpt_cfg,
                                    trial_id=f"t{i:05d}"))
        return trials or [Trial({}, checkpoint_config=ckpt_cfg)]

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_search_properties"):
            scheduler.set_search_properties(tc.metric, tc.mode)
        stop = self.run_config.stop
        stopper: Optional[Stopper] = None
        stop_criteria: Dict[str, Any] = {}
        if isinstance(stop, Stopper):
            stopper = stop
        elif callable(stop):
            stopper = FunctionStopper(stop)
        elif isinstance(stop, dict):
            stop_criteria = stop

        self._trials = self._make_trials()
        runner = TrialRunner(
            self.trainable_cls, self._trials,
            scheduler=scheduler, stopper=stopper,
            stop_criteria=stop_criteria,
            failure_config=self.run_config.failure_config,
            max_concurrent_trials=tc.max_concurrent_trials,
            resources_per_trial=tc.resources_per_trial,
            callbacks=list(self.run_config.callbacks) + [
                _SearcherCallback(tc.search_alg)] if tc.search_alg
            else list(self.run_config.callbacks),
        )
        runner.run()
        return ResultGrid(self._trials)

    def get_results(self) -> ResultGrid:
        if self._trials is None:
            raise RuntimeError("call fit() first")
        return ResultGrid(self._trials)


class _SearcherCallback:
    def __init__(self, searcher: Optional[Searcher]):
        self.searcher = searcher

    def on_trial_result(self, trial=None, result=None):
        if self.searcher:
            self.searcher.on_trial_result(trial.trial_id, result)

    def on_trial_complete(self, trial=None):
        if self.searcher:
            self.searcher.on_trial_complete(
                trial.trial_id, trial.last_result,
                error=trial.error is not None)


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler=None, search_alg=None,
        stop=None, resources_per_trial: Optional[dict] = None,
        max_concurrent_trials: Optional[int] = None,
        **_ignored) -> ResultGrid:
    """`tune.run` compatibility shim over Tuner."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            resources_per_trial=resources_per_trial or {"CPU": 1}),
        run_config=RunConfig(stop=stop),
    )
    return tuner.fit()
