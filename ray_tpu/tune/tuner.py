"""Tuner: the HPO entry point.

Reference: `python/ray/tune/tuner.py` + `tune/impl/tuner_internal.py` +
`tune.run` (`tune/tune.py`). `Tuner(trainable, param_space=...).fit()`
expands the param space into trials, runs them through the TrialRunner,
and returns a ResultGrid.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.basic_variant import generate_variants
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.stopper import FunctionStopper, Stopper
from ray_tpu.tune.trainable import Trainable, wrap_function


@dataclass
class TuneConfig:
    """Reference: `tune/tune_config.py`."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1})


class Tuner:
    def __init__(self, trainable: Union[Callable, type], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"unsupported trainable: {trainable!r}")
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._trials: Optional[List[Trial]] = None
        self._restored_trials: Optional[List[Trial]] = None

    # -- experiment durability -------------------------------------------
    # Reference: TrialRunner experiment checkpointing
    # (`tune/execution/trial_runner.py:427`) + `Tuner.restore`
    # (`tune/tuner.py` restore path): trial registry + per-trial latest
    # checkpoints snapshot to `<storage_path>/<name>/experiment_state.pkl`
    # on every trial event; `Tuner.restore` resumes unfinished trials
    # from their last checkpoints.

    def _experiment_dir(self) -> Optional[str]:
        if not self.run_config.storage_path:
            return None
        import os

        name = self.run_config.name or "experiment"
        path = os.path.join(self.run_config.storage_path, name)
        os.makedirs(path, exist_ok=True)
        return path

    def _save_experiment_state(self) -> None:
        path = self._experiment_dir()
        if path is None or self._trials is None:
            return
        import os

        import cloudpickle

        # Checkpoint payloads are serialized once per distinct checkpoint
        # object, not on every trial event — to_dict() on a
        # directory-backed checkpoint loads the full model state.
        cache = getattr(self, "_ckpt_dict_cache", None)
        if cache is None:
            cache = self._ckpt_dict_cache = {}

        def ckpt_dict(t):
            ckpt = t.checkpoint
            if ckpt is None:
                return None
            cached = cache.get(t.trial_id)
            if cached is not None and cached[0] is ckpt:
                return cached[1]
            data = ckpt.to_dict()
            cache[t.trial_id] = (ckpt, data)
            return data

        state = {
            "param_space": self.param_space,
            "tune_config": self.tune_config,
            "run_config": self.run_config,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "name": t.name,
                    "config": t.config,
                    "status": t.status,
                    "results": t.results,
                    "last_result": t.last_result,
                    "num_failures": t.num_failures,
                    "checkpoint": ckpt_dict(t),
                }
                for t in self._trials
            ],
        }
        target = os.path.join(path, "experiment_state.pkl")
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(cloudpickle.dumps(state))
        os.replace(tmp, target)  # atomic: a crash never corrupts state
        self._maybe_sync(on_checkpoint=True)

    def _maybe_sync(self, *, force: bool = False,
                    on_checkpoint: bool = False) -> None:
        sync_cfg = self.run_config.sync_config
        if sync_cfg is None:
            return
        cb = getattr(self, "_syncer_cb", None)
        if cb is None:
            from ray_tpu.tune.syncer import SyncerCallback

            cb = self._syncer_cb = SyncerCallback(
                sync_cfg, self._experiment_dir())
        cb.maybe_sync(force=force, on_checkpoint=on_checkpoint)

    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, type]) -> "Tuner":
        """Resume an interrupted experiment from its state file: finished
        trials keep their results; unfinished trials re-run from their
        last checkpoint."""
        import os
        import pickle

        from ray_tpu.air.checkpoint import Checkpoint

        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = pickle.loads(f.read())
        tuner = cls(trainable, param_space=state["param_space"],
                    tune_config=state["tune_config"],
                    run_config=state["run_config"])
        ckpt_cfg = tuner.run_config.checkpoint_config
        trials: List[Trial] = []
        for ts in state["trials"]:
            trial = Trial(ts["config"], checkpoint_config=ckpt_cfg,
                          trial_id=ts["trial_id"], name=ts["name"])
            trial.results = list(ts["results"])
            trial.last_result = dict(ts["last_result"])
            for r in trial.results:
                for k, v in r.items():
                    if isinstance(v, (int, float)):
                        trial.metric_history.setdefault(k, []).append(
                            float(v))
            trial.num_failures = ts["num_failures"]
            if ts["checkpoint"] is not None:
                trial.checkpoint_manager.register(
                    Checkpoint.from_dict(ts["checkpoint"]),
                    ts["last_result"])
            # Finished trials stay finished; everything else re-runs
            # (from the registered checkpoint when there is one).
            trial.status = Trial.TERMINATED \
                if ts["status"] == Trial.TERMINATED else Trial.PENDING
            trials.append(trial)
        tuner._restored_trials = trials
        return tuner

    def _searcher_cap(self) -> int:
        """Concurrency for searcher-driven runs — also the runner's cap,
        so a resumed run can't burst-suggest past what a fresh run of
        the same config would allow."""
        tc = self.tune_config
        return tc.max_concurrent_trials or max(1, min(tc.num_samples, 8))

    def _setup_lazy_suggestions(self, start: int):
        """Install the runner-facing trial generator; returns it."""
        tc = self.tune_config
        ckpt_cfg = self.run_config.checkpoint_config
        self._suggest_count = start

        def next_trial():
            if self._suggest_count >= tc.num_samples:
                return None
            tid = f"t{self._suggest_count:05d}"
            cfg = tc.search_alg.suggest(tid)
            if cfg is None:
                return None
            self._suggest_count += 1
            return Trial(cfg, checkpoint_config=ckpt_cfg, trial_id=tid)

        self._next_trial = next_trial
        self._suggest_exhausted = (
            lambda: self._suggest_count >= tc.num_samples)
        return next_trial

    def _make_trials(self) -> List[Trial]:
        tc = self.tune_config
        ckpt_cfg = self.run_config.checkpoint_config
        trials: List[Trial] = []
        if tc.search_alg is not None:
            tc.search_alg.set_search_properties(tc.metric, tc.mode,
                                                self.param_space)
            # LAZY suggestion (reference: SearchGenerator): only an
            # initial concurrency batch up front; the runner pulls the
            # rest one-by-one as slots free, so model-based searchers
            # (TPE/BOHB/Optuna) see completed results before suggesting
            # later configs — suggesting all num_samples here would
            # degrade every such searcher to random search.
            next_trial = self._setup_lazy_suggestions(start=0)
            cap = self._searcher_cap()
            for _ in range(min(cap, tc.num_samples)):
                t = next_trial()
                if t is None:
                    break
                trials.append(t)
            # Possibly empty (e.g. a limiter's "not now"): the runner's
            # generator pulls real trials later — never fabricate a
            # bogus empty-config trial.
            return trials
        else:
            for i, cfg in enumerate(generate_variants(
                    self.param_space, tc.num_samples, tc.seed)):
                trials.append(Trial(cfg, checkpoint_config=ckpt_cfg,
                                    trial_id=f"t{i:05d}"))
        return trials or [Trial({}, checkpoint_config=ckpt_cfg)]

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_search_properties"):
            scheduler.set_search_properties(tc.metric, tc.mode)
        stop = self.run_config.stop
        stopper: Optional[Stopper] = None
        stop_criteria: Dict[str, Any] = {}
        if isinstance(stop, Stopper):
            stopper = stop
        elif callable(stop):
            stopper = FunctionStopper(stop)
        elif isinstance(stop, dict):
            stop_criteria = stop

        self._trials = self._restored_trials or self._make_trials()
        if self._restored_trials is not None and \
                self.tune_config.search_alg is not None:
            # Resumed searcher experiment: continue lazy generation from
            # where the interrupted run stopped (the searcher object was
            # pickled WITH its observation state in tune_config).
            self._setup_lazy_suggestions(start=len(self._trials))
        callbacks = list(self.run_config.callbacks)
        if tc.search_alg:
            callbacks.append(_SearcherCallback(tc.search_alg))
        if self.run_config.storage_path:
            callbacks.append(_ExperimentSaver(self))
            self._save_experiment_state()
        runner = TrialRunner(
            self.trainable_cls, self._trials,
            scheduler=scheduler, stopper=stopper,
            stop_criteria=stop_criteria,
            failure_config=self.run_config.failure_config,
            max_concurrent_trials=(self._searcher_cap()
                                   if tc.search_alg is not None
                                   else tc.max_concurrent_trials),
            resources_per_trial=tc.resources_per_trial,
            callbacks=callbacks,
            trial_generator=getattr(self, "_next_trial", None),
            generator_exhausted=getattr(self, "_suggest_exhausted",
                                        None),
        )
        runner.run()
        if self.run_config.storage_path:
            self._save_experiment_state()
            cb = getattr(self, "_syncer_cb", None)
            if cb is not None:
                cb.close()  # final forced upload, wait for in-flight
        return ResultGrid(self._trials)

    def get_results(self) -> ResultGrid:
        if self._trials is None:
            raise RuntimeError("call fit() first")
        return ResultGrid(self._trials)


class _ExperimentSaver:
    """Snapshot experiment state on every trial event (reference:
    `trial_runner.py:427` checkpointing cadence, collapsed to
    event-driven since trials report at human timescales here)."""

    def __init__(self, tuner: Tuner):
        self.tuner = tuner

    def on_trial_start(self, trial=None):
        self.tuner._save_experiment_state()

    def on_trial_result(self, trial=None, result=None):
        self.tuner._save_experiment_state()

    def on_trial_complete(self, trial=None):
        self.tuner._save_experiment_state()


class _SearcherCallback:
    def __init__(self, searcher: Optional[Searcher]):
        self.searcher = searcher

    def on_trial_result(self, trial=None, result=None):
        if self.searcher:
            self.searcher.on_trial_result(trial.trial_id, result)

    def on_trial_complete(self, trial=None):
        if self.searcher:
            self.searcher.on_trial_complete(
                trial.trial_id, trial.last_result,
                error=trial.error is not None)


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler=None, search_alg=None,
        stop=None, resources_per_trial: Optional[dict] = None,
        max_concurrent_trials: Optional[int] = None,
        **_ignored) -> ResultGrid:
    """`tune.run` compatibility shim over Tuner."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            resources_per_trial=resources_per_trial or {"CPU": 1}),
        run_config=RunConfig(stop=stop),
    )
    return tuner.fit()
