"""Trial schedulers: early stopping + population-based training.

Reference: `python/ray/tune/schedulers/` — FIFO, ASHA
(`async_hyperband.py`), MedianStopping (`median_stopping_rule.py`), PBT
(`pbt.py`: exploit bottom-quantile trials from top performers +
perturb). Decisions are returned per result: CONTINUE / STOP / and for
PBT, a clone instruction executed by the runner via checkpoint restore.
"""

from __future__ import annotations

import math
import random as _random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.experiment.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def on_trial_result(self, runner, trial: Trial,
                        result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, runner, trial: Trial,
                          result: Optional[Dict[str, Any]] = None):
        pass

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True


class FIFOScheduler(TrialScheduler):
    metric = None
    mode = "max"


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference `tune/schedulers/async_hyperband.py`): successive
    halving with asynchronous rung promotion — at each rung, a trial stops
    unless its metric is in the top 1/reduction_factor of results recorded
    at that rung."""

    def __init__(self, *, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones: grace * rf^k up to max_t
        self.rungs: List[float] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[float, List[float]] = {r: []
                                                       for r in self.rungs}
        self._trial_rung: Dict[str, int] = {}
        # Per-trial value recorded at EACH rung it passed (not just the
        # last): the eager re-check below compares a trial's own
        # rung-time score against that rung's now-populated cutoff.
        self._trial_rung_values: Dict[str, Dict[float, float]] = {}

    def _sign(self, v: float) -> float:
        return v if self.mode == "max" else -v

    def _below_cutoff(self, rung: float, value: float) -> bool:
        recorded = self.rung_results[rung]
        if len(recorded) < self.rf:
            return False
        cutoff = sorted(recorded, reverse=True)[
            max(0, int(len(recorded) / self.rf) - 1)]
        return value < cutoff

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        idx = self._trial_rung.get(trial.trial_id, 0)
        mine = self._trial_rung_values.setdefault(trial.trial_id, {})
        while idx < len(self.rungs) and t >= self.rungs[idx]:
            rung = self.rungs[idx]
            self.rung_results[rung].append(self._sign(metric))
            mine[rung] = self._sign(metric)
            idx += 1
            self._trial_rung[trial.trial_id] = idx
            if self._below_cutoff(rung, self._sign(metric)):
                return self.STOP
        # Eager re-check against EVERY passed rung: a trial that sprinted
        # past rungs before peers arrived (e.g. buffered results, lockstep
        # execution) is re-evaluated with its OWN rung-time score once
        # those rungs populate — checking only the last rung let such a
        # trial escape culling and ride to max_t.
        for rung, value in mine.items():
            if self._below_cutoff(rung, value):
                return self.STOP
        return self.CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    other trials' averages at the same step."""

    def __init__(self, *, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 5, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        metric = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if metric is None:
            return self.CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(metric if self.mode == "max" else -metric)
        if t < self.grace_period or len(self._avgs) < self.min_samples:
            return self.CONTINUE
        my_avg = sum(hist) / len(hist)
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples - 1:
            return self.CONTINUE
        others.sort()
        median = others[len(others) // 2]
        return self.STOP if my_avg < median else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference `tune/schedulers/pbt.py`): every
    `perturbation_interval` steps, bottom-quantile trials clone a top
    performer's checkpoint and perturb its hyperparameters (×1.2 / ×0.8 or
    resample)."""

    def __init__(self, *, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed=None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = _random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}

    def _sign(self, v):
        return v if self.mode == "max" else -v

    def on_trial_result(self, runner, trial, result) -> str:
        metric = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if metric is None:
            return self.CONTINUE
        self._scores[trial.trial_id] = self._sign(metric)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scores = sorted(self._scores.values())
        if len(scores) < 2:
            return self.CONTINUE
        k = max(1, int(len(scores) * self.quantile))
        lower_cut = scores[k - 1]
        upper_cut = scores[-k]
        mine = self._scores[trial.trial_id]
        if mine > lower_cut or mine >= upper_cut:
            return self.CONTINUE
        # Exploit: pick a random top-quantile trial with a checkpoint.
        top = [tr for tr in runner.trials
               if self._scores.get(tr.trial_id, -math.inf) >= upper_cut
               and tr.checkpoint is not None and tr is not trial]
        if not top:
            return self.CONTINUE
        donor = self._rng.choice(top)
        new_config = self._explore(donor.config)
        runner.clone_trial(trial, donor, new_config)
        return self.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search.sample import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or \
                    key not in out:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                cur = out[key]
                if isinstance(spec, list):
                    # nudge to a neighbouring listed value
                    try:
                        i = spec.index(cur)
                        j = min(max(i + self._rng.choice([-1, 1]), 0),
                                len(spec) - 1)
                        out[key] = spec[j]
                    except ValueError:
                        out[key] = self._rng.choice(spec)
                elif isinstance(cur, (int, float)):
                    factor = self._rng.choice([0.8, 1.2])
                    out[key] = type(cur)(cur * factor)
        return out


class HyperBandScheduler(TrialScheduler):
    """HyperBand (reference `tune/schedulers/hyperband.py`): multiple
    successive-halving brackets trading off exploration breadth against
    per-trial budget. Bracket ``i`` starts halving at
    ``grace_period * reduction_factor**i``, so some brackets cull early
    and aggressively while others give every trial a longer run.

    Divergence from the reference, on purpose: rung promotion is
    asynchronous (ASHA-style) within each bracket — the runner here has
    no trial PAUSE support, and Li et al.'s asynchronous variant
    dominates the synchronous one in practice anyway.
    """

    def __init__(self, *, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3,
                 brackets: int = 3, grace_period: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self._brackets = [
            AsyncHyperBandScheduler(
                metric=metric, mode=mode, time_attr=time_attr,
                max_t=max_t,
                grace_period=int(grace_period * reduction_factor ** i),
                reduction_factor=reduction_factor)
            for i in range(max(1, brackets))
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def set_search_properties(self, metric, mode) -> bool:
        super().set_search_properties(metric, mode)
        for b in self._brackets:
            b.set_search_properties(metric, mode)
        return True

    def _bracket_of(self, trial) -> "AsyncHyperBandScheduler":
        idx = self._assignment.get(trial.trial_id)
        if idx is None:
            # Round-robin assignment: matches the reference's spreading
            # of trials over brackets as they arrive.
            idx = self._next % len(self._brackets)
            self._assignment[trial.trial_id] = idx
            self._next += 1
        return self._brackets[idx]

    def on_trial_result(self, runner, trial, result) -> str:
        return self._bracket_of(trial).on_trial_result(runner, trial,
                                                       result)


class PB2(PopulationBasedTraining):
    """PB2 (reference `tune/schedulers/pb2.py`, Parker-Holder et al.):
    population-based training whose EXPLORE step replaces random
    perturbation with a GP-bandit — a Gaussian process fit to
    (hyperparameters → score improvement) across the population proposes
    the UCB-maximizing config inside `hyperparam_bounds`.

    The GP is a self-contained numpy RBF implementation (the reference
    wraps GPy; not in this image), with UCB maximized by random search
    over the bounds — faithful to the algorithm, minimal machinery.
    """

    def __init__(self, *, hyperparam_bounds: Dict[str, Any],
                 ucb_beta: float = 2.0, candidates: int = 256,
                 **kwargs):
        # Mutations resample uniformly inside the bounds — _explore
        # overrides them with the GP, but any base-class fallback path
        # must still respect the bounds (a constant placeholder would
        # let e.g. a learning rate escape to 0).
        super().__init__(hyperparam_mutations={
            k: (lambda lo=lo, hi=hi:
                lo + _random.random() * (hi - lo))
            for k, (lo, hi) in hyperparam_bounds.items()}, **kwargs)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.ucb_beta = ucb_beta
        self.candidates = candidates
        self._prev_score: Dict[str, float] = {}
        # observations: (normalized hp vector, score delta)
        self._X: List[List[float]] = []
        self._y: List[float] = []

    def _norm(self, config) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_trial_result(self, runner, trial, result) -> str:
        metric = result.get(self.metric)
        if metric is not None:
            score = self._sign(metric)
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                self._X.append(self._norm(trial.config))
                self._y.append(score - prev)
                # Bounded history: the GP is O(n^3); old dynamics stop
                # describing the current regime anyway (the reference
                # keeps a sliding window too).
                if len(self._y) > 200:
                    self._X = self._X[-200:]
                    self._y = self._y[-200:]
            self._prev_score[trial.trial_id] = score
        old_config = trial.config
        decision = super().on_trial_result(runner, trial, result)
        if trial.config is not old_config:
            # The trial was just cloned from a donor checkpoint: its next
            # score delta reflects the checkpoint swap, not the explored
            # config — recording it would feed the GP spurious jumps.
            self._prev_score.pop(trial.trial_id, None)
        return decision

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        keys = list(self.bounds.keys())
        if len(self._y) < 4:
            # Cold start: uniform resample inside the bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                v = lo + self._rng.random() * (hi - lo)
                out[k] = type(config.get(k, v))(v) \
                    if isinstance(config.get(k), int) else v
            return out

        X = np.asarray(self._X)
        y = np.asarray(self._y)
        y_std = y.std() or 1.0
        yn = (y - y.mean()) / y_std
        ls, noise = 0.2, 1e-3
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-d2 / (2 * ls * ls)) + noise * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        except np.linalg.LinAlgError:
            # Degenerate GP: uniform resample INSIDE the bounds (same as
            # cold start) — never the base perturbation, whose x0.8/x1.2
            # nudges could walk outside hyperparam_bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                v = lo + self._rng.random() * (hi - lo)
                out[k] = int(round(v)) if isinstance(config.get(k), int) \
                    else v
            return out

        cand = np.asarray([
            [self._rng.random() for _ in keys]
            for _ in range(self.candidates)
        ])
        d2c = ((cand[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Kc = np.exp(-d2c / (2 * ls * ls))
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.maximum(1.0 - (v * v).sum(0), 1e-9)
        ucb = mu + self.ucb_beta * np.sqrt(var)
        best = cand[int(ucb.argmax())]
        for k, u in zip(keys, best):
            lo, hi = self.bounds[k]
            val = lo + float(u) * (hi - lo)
            out[k] = int(round(val)) if isinstance(config.get(k), int) \
                else val
        return out


class ResourceChangingScheduler(TrialScheduler):
    """Reference `tune/schedulers/resource_changing_scheduler.py`: wraps
    a base scheduler and reallocates trial resources mid-run via a user
    policy; a changed trial checkpoints, stops, and restarts with the
    new resources."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc_fn = resources_allocation_function

    @property
    def metric(self):
        return getattr(self.base, "metric", None)

    @property
    def mode(self):
        return getattr(self.base, "mode", "max")

    def set_search_properties(self, metric, mode) -> bool:
        return self.base.set_search_properties(metric, mode)

    def on_trial_result(self, runner, trial, result) -> str:
        decision = self.base.on_trial_result(runner, trial, result)
        if decision == self.CONTINUE and self.alloc_fn is not None:
            new_res = self.alloc_fn(runner, trial, result)
            if new_res and new_res != (trial.resources or
                                       runner.resources_per_trial):
                runner.update_trial_resources(trial, new_res)
        return decision

    def on_trial_complete(self, runner, trial, result=None):
        self.base.on_trial_complete(runner, trial, result)
