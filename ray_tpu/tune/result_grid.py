"""ResultGrid + ExperimentAnalysis (reference `tune/result_grid.py`,
`tune/analysis/experiment_analysis.py`)."""

from __future__ import annotations

from typing import List

from ray_tpu.air.result import Result
from ray_tpu.tune.experiment.trial import Trial


class ResultGrid:
    def __init__(self, trials: List[Trial]):
        self._trials = trials
        self._results = [self._to_result(t) for t in trials]

    @staticmethod
    def _to_result(trial: Trial) -> Result:
        metrics = dict(trial.last_result)
        metrics["config"] = trial.config
        metrics["trial_id"] = trial.trial_id
        return Result(
            metrics=metrics,
            checkpoint=trial.checkpoint_manager.best_checkpoint,
            error=trial.error,
            metrics_history=trial.results,
            best_checkpoints=trial.checkpoint_manager.best_checkpoints(),
        )

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._trials
                   if t.status == Trial.TERMINATED)

    def get_best_result(self, metric: str, mode: str = "max") -> Result:
        valid = [r for r in self._results
                 if r.metrics and metric in r.metrics]
        if not valid:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = (lambda r: r.metrics[metric])
        return max(valid, key=key) if mode == "max" else min(valid, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {k: v for k, v in (r.metrics or {}).items()
                   if not isinstance(v, dict)}
            for ck, cv in (r.metrics or {}).get("config", {}).items():
                row[f"config/{ck}"] = cv
            rows.append(row)
        return pd.DataFrame(rows)


class ExperimentAnalysis(ResultGrid):
    """Thin alias for reference API parity."""

    @property
    def best_result(self):  # pragma: no cover - convenience
        raise AttributeError("use get_best_result(metric, mode)")
