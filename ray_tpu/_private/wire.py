"""Typed wire contracts for the control plane.

Role-equivalent to the reference's protobuf contracts
(`src/ray/protobuf/*.proto`): every cross-process control message has a
declared, versioned schema, and the byte format is a small
self-describing binary encoding — NOT pickle. Pickle (cloudpickle) is
confined to explicitly-`Opaque` fields (user functions/args/results),
so the envelope and standard control traffic never require arbitrary
deserialization; a receiver validates field types against the declared
schema at decode time and rejects unknown message types and
newer-than-known schema versions instead of guessing.

Format (tag byte + payload, recursive):
  N nil · T/F bool · i int64 · I bignum · d float64 · s str · b bytes ·
  l list · t tuple · m dict · M registered message · O opaque(cloudpickle)

Messages are dataclasses registered with `@message("Name", version=N)`;
their annotated field types (int/float/str/bytes/bool/dict/list or Any)
are enforced on decode — the .proto-file role, in Python.
"""

from __future__ import annotations

import dataclasses
import struct
import typing
from typing import Any, Dict, Tuple

import cloudpickle
import pickle

_U32 = struct.Struct("!I")
_I64 = struct.Struct("!q")
_F64 = struct.Struct("!d")
_U16 = struct.Struct("!H")

# Container-nesting bound on decode. Real control messages nest a
# handful of levels (envelope → kwargs → values); a hostile frame of
# repeated list headers would otherwise drive the recursive decoder
# into RecursionError — an untyped escape that kills the connection
# thread instead of producing a clean typed rejection.
_MAX_DEPTH = 64


class WireError(ValueError):
    pass


class Opaque:
    """Explicitly pickled payload (user code/args). The ONLY place the
    wire format admits pickle — everything else is structural."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value


# -- message registry -------------------------------------------------------

_REGISTRY: Dict[str, Tuple[type, int]] = {}  # raylint: disable=R7 -- the wire message catalog is append-only BY CONTRACT: entries are versioned decode targets registered at import, removal would make in-flight frames of a still-spoken version undecodable; bounded by the set of @message classes in the codebase

_SCALAR_CHECKS = {
    int: int, float: (int, float), str: str, bytes: bytes, bool: bool,
    dict: dict, list: list, tuple: tuple,
}


def message(name: str, version: int = 1):
    """Register a dataclass as a wire message type (a .proto entry)."""

    def wrap(cls):
        cls = dataclasses.dataclass(cls)
        cls._wire_name = name
        cls._wire_version = version
        _REGISTRY[name] = (cls, version)
        return cls

    return wrap


_FIELDS_CACHE: dict = {}  # raylint: disable=R7 -- decode-plan memo keyed by registered message class: bounded by the catalog above and holds only derived (recomputable) data, so eviction could never reclaim anything the registry itself doesn't pin


def _declared_fields(cls) -> dict:
    """Per-class decode plan, computed once: field name -> (base type
    name, isinstance check tuple or None). Resolving string annotations
    (`from __future__ import annotations` makes every field type a
    string) via get_type_hints PER MESSAGE dominated decode cost."""
    plan = _FIELDS_CACHE.get(cls)
    if plan is None:
        hints = None
        plan = {}
        for f in dataclasses.fields(cls):
            ftype = f.type
            if isinstance(ftype, str):
                if hints is None:
                    try:
                        hints = typing.get_type_hints(cls)
                    except Exception:
                        hints = {}
                ftype = hints.get(f.name, Any)
            if ftype is Any:
                plan[f.name] = ("Any", None)
            else:
                origin = typing.get_origin(ftype)
                base = origin or ftype
                plan[f.name] = (getattr(base, "__name__", str(base)),
                                _SCALAR_CHECKS.get(base))
        _FIELDS_CACHE[cls] = plan
    return plan


def _check_field(cls, fname: str, entry, value):
    base_name, expect = entry
    if value is None or expect is None:
        return
    if not isinstance(value, expect):
        raise WireError(
            f"{cls._wire_name}.{fname}: expected {base_name}, got "
            f"{type(value).__name__}")


# -- encode -----------------------------------------------------------------


def _enc_str(out: bytearray, s: str):
    raw = s.encode()
    out += _U32.pack(len(raw))
    out += raw


def _encode_value(out: bytearray, v: Any):
    if v is None:
        out += b"N"
    elif v is True:
        out += b"T"
    elif v is False:
        out += b"F"
    elif isinstance(v, int):
        if -(2 ** 63) <= v < 2 ** 63:
            out += b"i"
            out += _I64.pack(v)
        else:
            out += b"I"
            raw = str(v).encode()
            out += _U32.pack(len(raw))
            out += raw
    elif isinstance(v, float):
        out += b"d"
        out += _F64.pack(v)
    elif isinstance(v, str):
        out += b"s"
        _enc_str(out, v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
        out += b"b"
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(v, (list, tuple)):
        out += b"l" if isinstance(v, list) else b"t"
        out += _U32.pack(len(v))
        for item in v:
            _encode_value(out, item)
    elif isinstance(v, dict):
        out += b"m"
        out += _U32.pack(len(v))
        for k, val in v.items():
            _encode_value(out, k)
            _encode_value(out, val)
    elif isinstance(v, Opaque):
        raw = cloudpickle.dumps(v.value)
        out += b"O"
        out += _U32.pack(len(raw))
        out += raw
    elif hasattr(type(v), "_wire_name"):
        out += b"M"
        _enc_str(out, type(v)._wire_name)
        out += _U16.pack(type(v)._wire_version)
        fields = dataclasses.fields(v)
        out += _U16.pack(len(fields))
        for f in fields:
            _enc_str(out, f.name)
            _encode_value(out, getattr(v, f.name))
    else:
        # Not a standard type and not declared: ship as opaque — the
        # receiver sees it tagged as pickled, never by surprise.
        raw = cloudpickle.dumps(v)
        out += b"O"
        out += _U32.pack(len(raw))
        out += raw


def encode(v: Any) -> bytes:
    out = bytearray()
    _encode_value(out, v)
    return bytes(out)


def encodes_natively(v: Any) -> bool:
    """True if v encodes without any opaque (pickle) section."""
    return b"O" not in _tags_of(encode(v))


def _tags_of(raw: bytes) -> bytes:
    # Walk the encoding collecting tag bytes (cheap structural check).
    tags = bytearray()
    _Decoder(raw, collect=tags).value()
    return bytes(tags)


# -- decode -----------------------------------------------------------------


class _Decoder:
    """Recursive-descent decoder over one received frame.

    Contract (the raywire fuzzer enforces it): ANY byte sequence either
    decodes or raises :class:`WireError` — no other exception type may
    escape, time is O(len(raw)), and nothing allocates beyond the bytes
    already received (every length field bounds-checks against the
    remaining buffer in ``_take`` before it is trusted)."""

    def __init__(self, raw: bytes, *, allow_opaque: bool = True,
                 collect: bytearray = None):
        self.raw = raw
        self.pos = 0
        self.depth = 0
        self.allow_opaque = allow_opaque
        self.collect = collect

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.raw):
            raise WireError("truncated message")
        chunk = self.raw[self.pos:self.pos + n]
        self.pos += n
        return chunk

    def _str(self) -> str:
        (n,) = _U32.unpack(self._take(4))
        raw = self._take(n)
        try:
            return raw.decode()
        except UnicodeDecodeError as e:
            raise WireError(f"invalid utf-8 in string: {e}") from None

    def _enter(self) -> None:
        self.depth += 1
        if self.depth > _MAX_DEPTH:
            raise WireError(
                f"container nesting exceeds {_MAX_DEPTH} levels")

    def value(self) -> Any:
        tag = self._take(1)
        if self.collect is not None:
            self.collect += tag
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            return _I64.unpack(self._take(8))[0]
        if tag == b"I":
            lit = self._str()
            try:
                return int(lit)
            except ValueError:
                # int() raises plain ValueError — WireError's BASE, so
                # a `except WireError` caller would NOT catch it.
                raise WireError(
                    f"malformed bignum literal {lit[:32]!r}") from None
        if tag == b"d":
            return _F64.unpack(self._take(8))[0]
        if tag == b"s":
            return self._str()
        if tag == b"b":
            (n,) = _U32.unpack(self._take(4))
            return self._take(n)
        if tag in (b"l", b"t"):
            (n,) = _U32.unpack(self._take(4))
            self._enter()
            items = [self.value() for _ in range(n)]
            self.depth -= 1
            return items if tag == b"l" else tuple(items)
        if tag == b"m":
            (n,) = _U32.unpack(self._take(4))
            self._enter()
            out = {}
            for _ in range(n):
                key = self.value()
                val = self.value()
                try:
                    out[key] = val
                except TypeError:
                    raise WireError(
                        "unhashable map key of type "
                        f"{type(key).__name__}") from None
            self.depth -= 1
            return out
        if tag == b"O":
            (n,) = _U32.unpack(self._take(4))
            raw = self._take(n)
            if self.collect is not None:
                return None  # structural walk: don't unpickle
            if not self.allow_opaque:
                raise WireError("opaque payload rejected by receiver")
            try:
                return pickle.loads(raw)
            except Exception as e:
                # Corrupt/hostile opaque sections raise the whole
                # pickle exception zoo (UnpicklingError, EOFError,
                # AttributeError, ImportError, ...): fold them into the
                # typed rejection so transports need exactly one catch.
                raise WireError(
                    "opaque payload failed to unpickle: "
                    f"{type(e).__name__}: {e}") from None
        if tag == b"M":
            self._enter()
            name = self._str()
            (version,) = _U16.unpack(self._take(2))
            (nfields,) = _U16.unpack(self._take(2))
            entry = _REGISTRY.get(name)
            if entry is None and self.collect is None:
                raise WireError(f"unknown message type {name!r}")
            cls, known_version = entry if entry else (None, version)
            if version > known_version and self.collect is None:
                raise WireError(
                    f"message {name} v{version} is newer than known "
                    f"v{known_version}; upgrade the receiver")
            kwargs = {}
            for _ in range(nfields):
                fname = self._str()
                fval = self.value()
                kwargs[fname] = fval
            if self.collect is not None:
                return None
            declared = _declared_fields(cls)
            clean = {}
            for fname, fval in kwargs.items():
                entry = declared.get(fname)
                if entry is None:
                    continue  # older receiver: skip newer fields
                _check_field(cls, fname, entry, fval)
                clean[fname] = fval
            self.depth -= 1
            try:
                return cls(**clean)
            except TypeError as e:
                # A frame omitting a field the receiver declares with
                # no default (schema skew the compat gate classifies as
                # breaking) must still reject as a typed wire failure.
                raise WireError(f"{name}: {e}") from None
        raise WireError(f"bad wire tag {tag!r}")


def decode(raw: bytes, *, allow_opaque: bool = True) -> Any:
    dec = _Decoder(raw, allow_opaque=allow_opaque)
    out = dec.value()
    if dec.pos != len(raw):
        raise WireError("trailing bytes after message")
    return out


# -- the control-plane contracts -------------------------------------------
# The envelope (every RPC) and the typed control messages. Adding a field
# is backward compatible (older receivers skip unknown fields); bumping
# `version` is the breaking-change gate (newer versions are rejected by
# older receivers with a clear error).


@message("rpc.Request", version=1)
class Request:
    id: str = ""           # "" = no exactly-once dedupe requested
    method: str = ""
    kwargs: Any = None     # dict; values may be Opaque
    # Highest sequence number this client has CONSUMED a reply for, or
    # -1 when unknown. Serialized request/reply clients implicitly ack
    # seq-1; pipelined clients have many requests outstanding, so the
    # server must not treat "saw seq N" as "replies < N were received".
    ack: int = -2          # -2 = field absent (legacy serialized client)


@message("rpc.Reply", version=1)
class Reply:
    ok: bool = True
    result: Any = None
    error: str = ""
    traceback: str = ""


@message("node.ResourceReport", version=1)
class ResourceReport:
    node_id: str = ""
    available: dict = None
    labels: dict = None
    stats: dict = None


@message("task.Template", version=1)
class TaskTemplate:
    """First shipment of an interned spec template to a node: the full
    invariant slice (SpecTemplate, cloudpickled — it carries the user
    function) plus its content-hash id. Subsequent submissions of the
    same shape reference the id via TaskCall."""

    template_id: bytes = b""
    payload: Any = None    # Opaque(SpecTemplate)


@message("object.Descriptor", version=1)
class ObjectDescriptor:
    """Object-plane handoff: instead of pickling a large payload into
    an RPC reply, the owner describes WHERE the sealed bytes live —
    the shared segment holding them and the native transfer endpoint
    serving them — and the requester reads zero-copy (same segment) or
    pulls the chunked native stream (cross segment/host). The framed-
    pickle value path remains for small objects and plane-less peers."""

    oid: bytes = b""
    shm: str = ""      # segment name holding the sealed payload
    host: str = ""     # transfer server endpoint ("" = not served)
    port: int = 0
    size: int = 0      # sealed payload bytes (pull sizing / stats)


@message("head.ShardRow", version=1)
class ShardRow:
    """One row mutation streamed to a head shard process
    (_private/head_shards.py): coalesced per-shard into shard_apply
    frames by the coordinator's CoalescingBatcher. ``value`` is the
    row payload (directory address tuple, size int, lineage edge
    bytes, ...); primitives encode natively, anything else rides
    Opaque like Request.kwargs values."""

    op: str = "put"        # "put" | "del"
    table: str = ""
    key: bytes = b""
    value: Any = None


@message("task.Call", version=1)
class TaskCall:
    """One task submission against an interned template: only the
    per-call fields travel. num_returns rides along (redundant with the
    template) so the receiver can fail THIS call into its return
    objects even when the template is missing."""

    template_id: bytes = b""
    task_id: bytes = b""
    args: Any = None           # Opaque(tuple) — may contain ObjectRefs
    kwargs: Any = None         # Opaque(dict)
    num_returns: Any = 1       # int | "dynamic"
    depth: int = 0
    trace_parent: Any = None   # (trace_id_hex, parent_span_id_hex) | None
    max_retries: int = 3
    # Job/tenant tag (added field: older receivers skip it) — rides the
    # header exactly like trace_parent so attribution survives the
    # interned fast path.
    job_id: str = ""
    # Retry ledger (added field): which dispatch attempt this call is
    # (0 = first; a node-death resubmit ships attempt+1 with the
    # already-decremented max_retries, so retry accounting survives the
    # interned fast path the same way job_id does).
    attempt: int = 0
