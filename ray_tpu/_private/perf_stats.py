"""Low-overhead fast-path statistics: the substrate under the runtime's
hot-path observability.

The metrics registry (`ray_tpu.util.metrics`) takes a lock per
observation — fine for user metrics, too heavy for paths PR 2 just
measured in microseconds (submit, wait, batcher flush). Stats here are
plain attribute/list increments under the GIL: a ``record()`` is two
integer adds and a float add, no lock, no allocation (the reference
keeps its equivalent fast-path stats in C++ thread-local OpenCensus
buffers for the same reason). Losing the occasional count to a data
race is acceptable for distributions; nothing here is load-bearing.

``collect_runtime_metrics()`` (``_private/runtime_metrics.py``) folds
these into the process metrics registry on every scrape, so they ride
the normal Prometheus exposition and — on cluster nodes — the metric
snapshots shipped to the head.

``ENABLED`` is the A/B kill switch: ``benchmarks/perf_bench.py
--ab-observability`` toggles it to prove the instrumentation tax on the
submit/wait hot paths stays under its budget.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Optional, Sequence, Tuple

ENABLED = True

# Latency bounds (seconds): 100µs .. 2.5s, roughly x2.5 steps — the
# control plane lives in this range.
LATENCY_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
# Serve request bounds (seconds): requests legitimately run to
# result_timeout_s (60s) — the control-plane bounds above would clamp
# a degraded route's p95 at 2.5s, hiding exactly what the metric is
# for.
SERVE_LATENCY_BOUNDS = LATENCY_BOUNDS + (5.0, 10.0, 30.0, 60.0, 120.0)
# Size bounds (items): powers of two up to one max frame.
SIZE_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

_registry_lock = threading.Lock()
_stats: Dict[Tuple[str, Tuple], "Dist | Counter"] = {}


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)


class Dist:
    """A value distribution over fixed buckets. ``record`` is lock-free
    (GIL-serialized increments); ``snapshot``/``quantile`` read a
    consistent-enough view for monitoring."""

    __slots__ = ("name", "tags", "bounds", "counts", "total", "sum")

    def __init__(self, name: str, tags: Tuple, bounds: Sequence[float]):
        self.name = name
        self.tags = tags
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0
        self.sum = 0.0

    def record(self, value: float) -> None:
        if not ENABLED:
            return
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound quantile estimate (0 when empty)."""
        total = self.total
        if total <= 0:
            return 0.0
        target = q * total
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1]
        return self.bounds[-1]

    def snapshot(self) -> dict:
        return {"kind": "dist", "bounds": list(self.bounds),
                "counts": list(self.counts), "count": self.total,
                "sum": self.sum}


class Counter:
    __slots__ = ("name", "tags", "value")

    def __init__(self, name: str, tags: Tuple):
        self.name = name
        self.tags = tags
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if ENABLED:
            self.value += n

    def snapshot(self) -> dict:
        return {"kind": "counter", "value": self.value}


def _norm_tags(tags: Optional[Dict[str, str]]) -> Tuple:
    if not tags:
        return ()
    return tuple(sorted(tags.items()))


def latency(name: str, tags: Optional[Dict[str, str]] = None) -> Dist:
    return _get(name, tags, lambda n, t: Dist(n, t, LATENCY_BOUNDS))


def dist(name: str, tags: Optional[Dict[str, str]] = None,
         bounds: Sequence[float] = SIZE_BOUNDS) -> Dist:
    return _get(name, tags, lambda n, t: Dist(n, t, bounds))


def counter(name: str, tags: Optional[Dict[str, str]] = None) -> Counter:
    return _get(name, tags, Counter)


def _get(name, tags, make):
    key = (name, _norm_tags(tags))
    stat = _stats.get(key)
    if stat is None:
        with _registry_lock:
            stat = _stats.get(key)
            if stat is None:
                stat = _stats[key] = make(name, key[1])
    return stat


def stats_items():
    """[(name, tags_tuple, stat)] — consumed by runtime_metrics."""
    with _registry_lock:
        return [(name, tags, stat)
                for (name, tags), stat in _stats.items()]


def reset() -> None:
    """Zero every stat IN PLACE (tests and the A/B bench). The hot
    paths hold module/instance references to their stat objects, so
    dropping registry entries would orphan them — recordings would keep
    landing in objects the exposition no longer sees."""
    with _registry_lock:
        for stat in _stats.values():
            if isinstance(stat, Dist):
                stat.counts = [0] * (len(stat.bounds) + 1)
                stat.total = 0
                stat.sum = 0.0
            else:
                stat.value = 0


def snapshot_records(name: str) -> dict:
    """Plain-data snapshot of every stat registered under ``name``:
    ``{tags_tuple: (counts_tuple, total, sum)}`` for dists,
    ``{tags_tuple: value}`` for counters. With :func:`restore_records`
    this is the reset-capable API around process-global records that
    tests (the ambient sanitizer, the conftest baseline fixture) use to
    guarantee one test's recordings never leak into the next."""
    out: dict = {}
    with _registry_lock:
        for (n, tags), stat in _stats.items():
            if n != name:
                continue
            if isinstance(stat, Dist):
                out[tags] = (tuple(stat.counts), stat.total, stat.sum)
            else:
                out[tags] = stat.value
    return out


def restore_records(name: str, snapshot: dict) -> None:
    """Restore ``name``'s stats to a :func:`snapshot_records` snapshot
    IN PLACE (same aliasing constraint as :func:`reset`). Tagged series
    created since the snapshot are zeroed — they cannot be deleted
    without orphaning live references, and zero is what the snapshot
    implies for them."""
    with _registry_lock:
        for (n, tags), stat in _stats.items():
            if n != name:
                continue
            saved = snapshot.get(tags)
            if isinstance(stat, Dist):
                if saved is None:
                    stat.counts = [0] * (len(stat.bounds) + 1)
                    stat.total = 0
                    stat.sum = 0.0
                else:
                    counts, total, total_sum = saved
                    stat.counts = list(counts)
                    stat.total = total
                    stat.sum = total_sum
            else:
                stat.value = 0 if saved is None else saved
