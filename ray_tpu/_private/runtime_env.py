"""Runtime environments: per-task/actor execution environments.

Reference: `python/ray/_private/runtime_env/` (SURVEY.md §2.2) — plugins
for env_vars / working_dir / pip / conda / py_modules, created on demand
by the per-node agent. In the in-process runtime, `env_vars` and
`working_dir` apply around task execution (serialized by a lock — process
env is global); `pip`/`conda` validate and record, materializing only
when worker *processes* launch (job supervisors pass them through).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Optional

_env_lock = threading.Lock()

KNOWN_FIELDS = {"env_vars", "working_dir", "pip", "conda", "py_modules",
                "container", "config"}

_PLUGINS: Dict[str, "RuntimeEnvPlugin"] = {}


class RuntimeEnvPlugin:
    """Reference: `runtime_env/plugin.py` ABC."""

    name: str = ""
    priority: int = 10

    def validate(self, value: Any) -> None:
        pass

    @contextlib.contextmanager
    def apply(self, value: Any):
        yield


def register_plugin(plugin: RuntimeEnvPlugin):
    _PLUGINS[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    """Remove a plugin (raylint R7: the registry needs a bounded
    lifetime — tests register throwaway plugins and must be able to
    take them back out)."""
    _PLUGINS.pop(name, None)


class _EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"

    def validate(self, value):
        if not isinstance(value, dict):
            raise TypeError("env_vars must be a dict of str->str")

    @contextlib.contextmanager
    def apply(self, value: Dict[str, str]):
        saved: Dict[str, Optional[str]] = {}
        for k, v in value.items():
            saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        try:
            yield
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old


class _WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"

    def validate(self, value):
        if not isinstance(value, str):
            raise TypeError("working_dir must be a path string")

    @contextlib.contextmanager
    def apply(self, value: str):
        old = os.getcwd()
        os.chdir(value)
        try:
            yield
        finally:
            os.chdir(old)


class _PyModulesPlugin(RuntimeEnvPlugin):
    """py_modules: directories or zip files whose modules become
    importable for the task (reference `runtime_env/py_modules.py`; the
    reference additionally ships the files via GCS — here paths must be
    reachable on the executing node, e.g. a shared filesystem)."""

    name = "py_modules"

    def validate(self, value):
        if not isinstance(value, (list, tuple)) or not all(
                isinstance(p, str) for p in value):
            raise TypeError("py_modules must be a list of path strings")

    @contextlib.contextmanager
    def apply(self, value):
        import importlib
        import sys

        added = []
        for path in value:
            # Each entry names a module: a package dir or a single .py
            # imports via its PARENT directory; a zip goes on sys.path
            # itself (zipimport).
            p = os.path.abspath(path.rstrip("/"))
            if os.path.isfile(p) and p.endswith(".zip"):
                entry = p
            else:
                entry = os.path.dirname(p)
            sys.path.insert(0, entry)
            added.append(entry)
        importlib.invalidate_caches()
        try:
            yield
        finally:
            for entry in added:
                try:
                    sys.path.remove(entry)
                except ValueError:
                    pass


class _RecordedOnlyPlugin(RuntimeEnvPlugin):
    """pip/conda: validated + recorded; materialized by worker-process
    launchers (job supervisor), not applicable to in-process threads."""

    def __init__(self, name: str):
        self.name = name


for _p in (_EnvVarsPlugin(), _WorkingDirPlugin(), _PyModulesPlugin(),
           _RecordedOnlyPlugin("pip"), _RecordedOnlyPlugin("conda"),
           _RecordedOnlyPlugin("container"),
           _RecordedOnlyPlugin("config")):
    register_plugin(_p)


def validate_runtime_env(runtime_env: Optional[dict]) -> None:
    if not runtime_env:
        return
    unknown = set(runtime_env) - KNOWN_FIELDS
    if unknown:
        raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
    for key, value in runtime_env.items():
        plugin = _PLUGINS.get(key)
        if plugin:
            plugin.validate(value)


@contextlib.contextmanager
def applied_runtime_env(runtime_env: Optional[dict]):
    """Apply an env around a task body. Serialized: process env/cwd are
    global, so concurrent tasks with envs take turns."""
    if not runtime_env or not any(
            k in runtime_env
            for k in ("env_vars", "working_dir", "py_modules")):
        yield
        return
    with _env_lock:
        with contextlib.ExitStack() as stack:
            for key in ("working_dir", "py_modules", "env_vars"):
                if key in runtime_env:
                    stack.enter_context(
                        _PLUGINS[key].apply(runtime_env[key]))
            yield
