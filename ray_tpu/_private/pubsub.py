"""Long-poll pub/sub: the control-plane event channel.

Role-equivalent to the reference's `src/ray/pubsub/` — a Publisher buffers
messages per channel; Subscribers long-poll with a cursor and get every
message published since (`publisher.h:188-216` is the same
buffer+long-poll shape). Used for node lifecycle events (NODE_ADDED /
NODE_DEAD), with channels open to any producer (the dashboard and state
API read the same stream).

Messages are (seq, payload) tuples; a bounded ring per channel means a
subscriber that sleeps too long misses old messages (it can resync from
authoritative state — same contract as the reference's pubsub, which is
a cache, not a log).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Tuple

_RING = 1024


class _Channel:
    def __init__(self):
        self.seq = 0
        self.buffer: List[Tuple[int, Any]] = []
        self.cond = threading.Condition()

    def publish(self, payload: Any) -> int:
        with self.cond:
            self.seq += 1
            self.buffer.append((self.seq, payload))
            if len(self.buffer) > _RING:
                del self.buffer[: len(self.buffer) - _RING]
            self.cond.notify_all()
            return self.seq

    def poll(self, cursor: int, timeout: float) -> Tuple[int, List[Any]]:
        deadline = time.monotonic() + timeout
        with self.cond:
            while True:
                newer = [(s, p) for (s, p) in self.buffer if s > cursor]
                if newer:
                    return newer[-1][0], [p for _, p in newer]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return cursor, []
                self.cond.wait(remaining)


class Publisher:
    """Server side: per-channel buffers, long-poll handler."""

    def __init__(self):
        self._lock = threading.Lock()
        self._channels: Dict[str, _Channel] = {}

    def _channel(self, name: str) -> _Channel:
        with self._lock:
            ch = self._channels.get(name)
            if ch is None:
                ch = self._channels[name] = _Channel()
            return ch

    def publish(self, channel: str, payload: Any) -> int:
        return self._channel(channel).publish(payload)

    def poll(self, channel: str, subscriber_id: str, cursor: int,
             timeout: float = 10.0) -> Dict[str, Any]:
        new_cursor, messages = self._channel(channel).poll(cursor, timeout)
        return {"cursor": new_cursor, "messages": messages}


class Subscriber:
    """Client side: a background long-poll loop per channel delivering to
    a callback. `rpc_call(channel, subscriber_id, cursor, timeout)` is the
    transport hook — in cluster mode bind it to a *dedicated* client
    (``RpcClient.dedicated(addr)``): the pooled per-address client
    serializes calls on one socket, and a long poll parked there would
    head-of-line block every other RPC to that address."""

    def __init__(self, rpc_call, subscriber_id: str):
        self._rpc_call = rpc_call
        self.subscriber_id = subscriber_id
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def subscribe(self, channel: str, callback) -> None:
        def loop():
            cursor = 0
            while not self._stop.is_set():
                try:
                    reply = self._rpc_call(
                        channel=channel, subscriber_id=self.subscriber_id,
                        cursor=cursor, timeout=5.0)
                except Exception:
                    if self._stop.wait(0.5):
                        return
                    continue
                cursor = reply["cursor"]
                for message in reply["messages"]:
                    try:
                        callback(message)
                    except Exception:  # subscriber bugs don't kill the loop
                        pass

        t = threading.Thread(target=loop, daemon=True,
                             name=f"pubsub-{channel}")
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
