"""Runtime configuration flags.

Role-equivalent to the reference's ``RAY_CONFIG`` table
(`src/ray/common/ray_config_def.h`: a macro table of 193 typed tunables,
overridable per-process via ``RAY_<name>`` environment variables or
``ray.init(_system_config=...)``). Here the table is a dataclass of typed
fields; overrides come from ``RAY_TPU_<NAME>`` env vars (checked at first
access) or ``ray_tpu.init(_system_config={...})``.

Usage::

    from ray_tpu._private.config import ray_config
    period = ray_config.health_check_period_s
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Optional


@dataclasses.dataclass
class RayTpuConfig:
    # -- failure detection (reference: gcs_health_check_manager.h:39,
    #    ray_config_def.h health_check_* flags) ---------------------------
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 3

    # -- object plane ----------------------------------------------------
    # Driver/node-side remote fetch gives up after this long without
    # locating an owner (reference: fetch_timeout_milliseconds).
    fetch_deadline_s: float = 60.0
    # Objects above this many bytes go to the shared segment / transfer
    # plane instead of inline pickle RPC.
    shm_share_threshold_bytes: int = 64 * 1024
    # Disk spill: objects spill when the in-process store exceeds this
    # fraction of its budget (reference: object_spilling_threshold).
    object_spilling_threshold: float = 0.8
    object_store_memory_bytes: int = 2 * 1024 ** 3
    min_spilling_size_bytes: int = 1024 * 1024
    # Bandwidth-aware pull bounding (reference: pull_manager.h caps
    # in-flight pull bytes): at most this many native wire pulls run at
    # once; excess callers wait for a slot (wait time lands in
    # perf_stats `object_pull_slot_wait_seconds`).
    object_pull_max_concurrent: int = 2
    # Parallel range-striped streams per native pull (transfer.h
    # pull_striped): each stream moves a disjoint byte range.
    object_pull_streams: int = 4
    # Object-arrival poll curve (cluster_utils.fetch_backoff): sleep
    # base * 1.6^attempt, capped. Sub-ms first probes — most objects
    # land within a few ms of submission — backing off for slow
    # producers.
    object_fetch_backoff_base_s: float = 0.0005
    object_fetch_backoff_cap_s: float = 0.01
    # Shared-segment arena spill: on create-failure backpressure the
    # owner spills its cold, unpinned shm objects to disk (URL on the
    # store entry, transparent restore on get) instead of looping on
    # eviction waits. Off = legacy wait-then-heap-fallback behavior.
    shm_spill_enabled: bool = True

    # -- locality-aware scheduling (reference: lease_policy.h locality-
    #    aware lease policy) ---------------------------------------------
    # Score lease placement by resident argument bytes so tasks with
    # large args run where the bytes already live instead of pulling
    # them to follow a small spec.
    locality_aware_scheduling: bool = True
    # Arguments below this many resident bytes never influence
    # placement (pulling them costs less than disturbing the pack).
    locality_min_arg_bytes: int = 1024 * 1024

    # -- lineage / reconstruction (reference: object_recovery_manager.h,
    #    task_manager.h lineage pinning) ---------------------------------
    enable_object_reconstruction: bool = True
    max_reconstruction_attempts: int = 3
    # Recursive reconstruction of a lost chain stops at this depth (a
    # lineage cycle or pathological dependency chain must terminate;
    # each OBJECT is still charged its own max_reconstruction_attempts).
    max_reconstruction_depth: int = 16

    # -- actor fault tolerance (reference: gcs_actor_manager.h restart
    #    FSM + direct_actor_task_submitter.h client-side queueing) ------
    # Calls submitted (or caught in flight) while an actor restarts park
    # this long waiting for the replacement before failing with an
    # ActorUnavailableError naming the restart state. Only calls with
    # max_task_retries > 0 park; others reject immediately.
    actor_restart_timeout_s: float = 30.0

    # -- rpc -------------------------------------------------------------
    rpc_connect_retries: int = 10
    rpc_retry_backoff_s: float = 0.5
    # Pre-allocation bound on one framed RPC message. The u32 length
    # prefix admits 4 GiB; without this cap the frame reader would
    # allocate whatever a hostile or skewed peer claims BEFORE any
    # byte of the body is validated. Over-cap frames raise
    # rpc.FrameTooLarge and drop the connection (the stream cannot be
    # resynchronized without reading the unread body).
    rpc_max_frame_bytes: int = 64 * 1024 * 1024
    # Mutual-TLS for the control plane (reference: RAY_USE_TLS +
    # RAY_TLS_SERVER_CERT/KEY/CA_CERT, rpc/grpc_server TLS creds). All
    # three paths must be set when use_tls is on; both sides verify the
    # peer against the shared CA.
    use_tls: bool = False
    tls_server_cert: str = ""
    tls_server_key: str = ""
    tls_ca_cert: str = ""

    # -- resource view sync (reference: ray_syncer.h RESOURCE_VIEW) ------
    # Nodes push availability deltas to the head at this period; the
    # scheduler reads the cached view instead of pinging per submission.
    resource_report_period_s: float = 0.1
    # A pushed report also counts as a heartbeat: nodes reporting within
    # this many periods are skipped by the active health checker.
    resource_report_fresh_periods: float = 5.0

    # -- scheduling ------------------------------------------------------
    # Pack below this node-utilization fraction, then prefer spreading
    # (reference: scheduler_spread_threshold, hybrid_scheduling_policy.h).
    scheduler_spread_threshold: float = 0.5
    # Compact queued representation: queued-but-undispatched normal
    # tasks are held as interned-template headers (QueuedTaskHeader)
    # and materialized to a full TaskSpec only at dispatch, so a
    # million-task backlog costs header bytes, not spec bytes
    # (reference: the serialize-once TaskSpec + raylet queued-lease
    # shape). Off = every submission builds the full spec up front.
    sched_compact_queue: bool = True
    # Shared-executor actors: sync max_concurrency=1 in-process actors
    # are served by the grow-on-demand executor pool (one activation at
    # a time per actor preserves mailbox order) instead of a dedicated
    # thread per actor, so 10k actors cost 10k mailboxes, not 10k
    # threads. Async / multi-concurrency / process-isolated actors
    # keep dedicated threads. Off = legacy thread-per-actor.
    sched_actor_executor_pool: bool = True
    # Group-committed actor creation: cluster-dispatched creations ride
    # the per-node CoalescingBatcher (submit_batch frames) and head
    # re-registrations batch into one report_actors RPC, so N actors
    # register in O(batches) head round trips. Restart-gate semantics
    # are unchanged (same record_lineage/ActorRestartGate.register
    # calls, batched transport). Off = one synchronous RPC per actor.
    sched_group_actor_creation: bool = True
    # Multi-slot pooled actors: sync in-process actors with
    # max_concurrency>1 (serve replicas declare it) are ALSO served by
    # the executor pool — up to max_concurrency concurrent drain
    # passes per actor instead of max_concurrency standing threads.
    # Off = only max_concurrency=1 actors pool (PR 13 behavior).
    sched_actor_pool_multislot: bool = True
    # Lock partitioning for the head's hot scheduling tables (inflight,
    # object directory, lineage, lease grants): shard count (rounded up
    # to a power of two). 1 = effectively a single lock per table.
    sched_head_shards: int = 16
    # Multi-PROCESS head control plane (distinct from the in-process
    # lock partitioning above): the hot row tables — object directory +
    # sizes, inflight, lineage edges, lease registrations — stream to N
    # head shard PROCESSES by stable key hash, each owning its own
    # group-commit durability window (_private/head_shards.py). 1 =
    # no shard processes, today's single-process head byte-for-byte.
    head_shards: int = 1
    # Each shard's sqlite group-commit window (its durability loss
    # bound on a hard crash). <= 0 means "inherit
    # gcs_commit_interval_s".
    head_shard_commit_interval_s: float = 0.0
    # Directory for the per-shard sqlite dbs; empty = a temp dir per
    # head (rows then survive shard restarts but not host cleanup).
    head_shard_db_dir: str = ""
    # Lease cache: a granted (job, shape) lease is returned after this
    # long idle (reference: lease return on idle worker).
    sched_lease_idle_s: float = 2.0
    # Spillback: a leased node whose reported backlog exceeds this many
    # queued-undispatched tasks triggers a spill lease on a better
    # target (reference: raylet backlog-driven spillback).
    sched_spillback_backlog: int = 128

    # -- memory monitor / worker killing (reference: memory_monitor.h) ---
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250

    # -- observability plane ---------------------------------------------
    # Worker nodes ship task-event deltas + metric-registry snapshots to
    # the head's aggregator at this period (reference: the GCS task
    # manager / OpenCensus export cadence). 0 disables shipping.
    obs_ship_period_s: float = 0.5
    # Max task events per shipping cycle — the rest stay queued for the
    # next cycle, so one burst never produces an unbounded frame.
    obs_ship_max_events: int = 2000
    # Head-side cluster event store bound (events beyond this are
    # evicted oldest-first).
    obs_head_max_events: int = 200_000
    # Serve HTTP access log: one structured line per request on the
    # "ray_tpu.serve.access" logger (method, route, status, latency_ms,
    # trace_id, job_id). Off by default — the ingress hot path stays
    # log-free.
    serve_access_log: bool = False

    # -- critical path / flight recorder (_private/critical_path.py,
    #    _private/flight_recorder.py) ------------------------------------
    # Stage-span recording at every request hop (the per-route
    # attribution vectors behind ray_tpu_request_stage_seconds and
    # /api/slow_requests). The --ab-observability bench flips this to
    # prove the tax on the serve keep-alive path stays under budget.
    stage_spans_enabled: bool = True
    # Where degradation-triggered FLIGHT_<ts>.json snapshots land.
    # Empty (the default) disables the auto-dump entirely — only an
    # explicit /api/debug/dump?write=1 or CLI request writes files.
    flight_recorder_dir: str = ""
    # Debounce: at most one auto-dump per this many seconds, so a
    # flapping verdict costs one snapshot per window, not one per
    # healthz poll.
    flight_min_interval_s: float = 60.0
    # Ring entries (spans / health samples) each process contributes
    # to a frozen snapshot.
    flight_ring_size: int = 512

    # -- serve data plane (proxy fleet + replica-direct dispatch) --------
    # Replica-direct dispatch: the HTTP proxy's steady-state fast path
    # dispatches proxy→replica over the long-poll-fed membership table
    # (no router lock, no per-request pruning, no head involvement),
    # falling back to the routed path on cache miss / saturation /
    # replica death. Read per request, so an A/B can flip it live.
    serve_replica_direct: bool = True
    # Priority-class load shedding (X-Priority: high|normal|low or
    # 0|1|2): class c is admitted while proxy in-flight < max_in_flight
    # * fraction[c], so the lowest class sheds first as load rises.
    # Defaults keep high/normal at the full cap (pre-priority behavior
    # for untagged traffic) and shed low-priority work at half load.
    serve_priority_shed_fractions: str = "1.0,1.0,0.5"
    # Optional per-class ingress token buckets ("low=50:100;normal=200",
    # rate[:burst] per second): a class over its rate sheds 503 +
    # Retry-After at the proxy even when in-flight headroom exists.
    serve_priority_rates: str = ""
    # Replica-health supervision: the controller pings each replica
    # every period; this many consecutive failures (timeout
    # serve_replica_health_timeout_s each) marks the replica dead — it
    # is removed from membership (broadcast FIRST, so routers and
    # direct tables stop dispatching), reported in /api/healthz, and
    # replaced by the reconcile loop.
    serve_replica_health_period_s: float = 1.0
    serve_replica_health_timeout_s: float = 2.0
    serve_replica_health_failures: int = 2
    # Proxy-fleet supervision period (ProxyFleet): dead proxies are
    # reported degraded and restarted on their original port.
    serve_proxy_supervise_period_s: float = 1.0
    # SLO-burn-driven autoscaling: a deployment whose route burns its
    # error budget past this multiple (short window; status-aware, so
    # load-shed 503s count) scales up one replica per cooldown even
    # when the queue signal alone would not — and never scales down
    # while burning. 0 disables the burn input (queue-only, PR 6
    # behavior).
    serve_autoscale_burn_threshold: float = 2.0
    serve_autoscale_cooldown_s: float = 3.0

    # -- SLO / health plane (_private/health.py) -------------------------
    # Per-route latency SLO targets: "route=latency_s[:objective],..."
    # (e.g. "/chat=0.25:0.999,/embed=0.1"). Routes not listed use the
    # defaults below. The burn-rate gauges and /api/healthz verdicts
    # are computed against these.
    serve_slo_targets: str = ""
    serve_slo_default_latency_s: float = 0.5
    serve_slo_default_objective: float = 0.99
    # Multi-window burn rates (the classic short/long burn-rate alert
    # shape) diffed from periodic cumulative-count snapshots.
    slo_burn_short_window_s: float = 30.0
    slo_burn_long_window_s: float = 300.0
    # Event-loop lag sampling period on the Serve proxy/replica loops
    # (0 disables the sampler).
    loop_lag_sample_period_s: float = 0.25
    # Degraded-verdict thresholds: memory usage fraction, scheduler
    # backlog (queued undispatched tasks), event-loop scheduling lag,
    # and SLO burn multiple (1.0 = burning the error budget exactly at
    # the sustainable rate).
    health_memory_pressure_threshold: float = 0.92
    health_backlog_threshold: int = 2000
    health_loop_lag_threshold_s: float = 0.25
    health_slo_burn_threshold: float = 4.0

    # -- tenancy enforcement (_private/tenancy.py; the enforcement half
    #    of the PR 6 attribution plane — reference: scheduling policies
    #    at lease grant + Serve ingress limits) --------------------------
    # Master switch: quotas, WFQ, ingress rate limits, and arena-budget
    # victim ordering all gate on this (attribution/metering is always
    # on). Off = PR 6 behavior exactly.
    tenancy_enforcement: bool = False
    # Per-job quotas: "jobA=cpus:2,queued:100,leases:2;jobB=cpus:1".
    # cpus bounds concurrently RUNNING CPU slots (over-quota tasks park
    # behind the job's own limit), queued bounds admitted-not-started
    # tasks (beyond it submits fail with JobQuotaExceededError), leases
    # bounds concurrently held pipelined dispatch leases.
    job_quotas: str = ""
    # WFQ weights for the scheduler's runnable queue and the serve
    # router: "jobA=4,jobB=1". Unlisted (and untagged) traffic uses
    # job_default_weight.
    job_weights: str = ""
    job_default_weight: float = 1.0
    # Ingress token buckets: "jobA=rate[:burst];..." per second, shed
    # with 429 + Retry-After BEFORE the router. 0 default rate = only
    # explicitly listed jobs are limited.
    ingress_rate_limits: str = ""
    ingress_default_rate_per_s: float = 0.0
    ingress_default_burst: float = 0.0
    # Optional shared-secret ingress auth: when set, requests must
    # carry "Authorization: Bearer <token>" or "X-Auth-Token: <token>"
    # or are refused with 401 before any routing work happens.
    ingress_auth_token: str = ""
    # Per-job shared-arena budgets: "jobA=64m;jobB=1g". A job over its
    # budget has ITS cold objects spilled first under arena pressure,
    # so its oversized working set cannot evict another tenant's.
    job_arena_budgets: str = ""

    # -- LLM serving (serve/llm.py + _private/kv_cache.py) ---------------
    # Prefix/KV cache: prefill skips the shared prompt head by copying
    # matched KV blocks from the host-side prefix cache into the slot
    # and prefilling only the tail. Off = every request prefills its
    # full prompt (pre-cache behavior; the bench A/B flips this).
    llm_prefix_cache: bool = True
    # Tokens per KV block (the prefix-match granularity; only full
    # blocks are cached, the partial tail chunk never is).
    llm_kv_block_tokens: int = 16
    # Host-side prefix cache capacity per engine; LRU unpinned blocks
    # evict past it (warm evictees fall to the shm tier below).
    llm_prefix_cache_bytes: int = 256 * 1024 * 1024
    # Shm-plane warm tier: evicted blocks persist as spill-backed
    # shared objects (charged to the owning tenant's arena budget) so
    # a cache hit on another replica restores via the object plane
    # instead of recomputing the prefill.
    llm_prefix_shm_tier: bool = True
    # Cache-affinity routing: replicas export hot prefix-head digests
    # through the membership long-poll; the replica-direct path scores
    # candidates by matched-prefix bytes (tie → least-loaded).
    llm_affinity_routing: bool = True
    # How many MRU block keys a replica exports in its digest.
    llm_digest_blocks: int = 32
    # How often the controller polls replicas for fresh digests (and
    # rebroadcasts the digests:: channel on change).
    llm_digest_refresh_s: float = 2.0
    # Multi-model cold-start SLA: a weight swap (load + device put)
    # exceeding this deadline fails the request with
    # ModelSwapDeadlineError (the loaded weights stay cached, so a
    # retry is warm). 0 disables the deadline.
    llm_model_swap_deadline_s: float = 30.0

    # -- GCS storage (reference: store_client/; "" = in-memory, a file
    #    path selects the durable SQLite backend in Redis's role) -------
    gcs_storage_path: str = ""
    # Durable-write group-commit window: registry writes landing within
    # this many seconds share ONE disk transaction (the reference's
    # async GCS-storage write path); 0 = synchronous commit per write.
    # flush() / graceful teardown force durability at the boundary.
    gcs_commit_interval_s: float = 0.005

    def reset(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, f.default)
        _apply_env_overrides(self)


def _coerce(raw: str, target_type: type) -> Any:
    if target_type is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return target_type(raw)


def _apply_env_overrides(cfg: RayTpuConfig) -> None:
    for f in dataclasses.fields(cfg):
        env = os.environ.get(f"RAY_TPU_{f.name.upper()}")
        if env is not None:
            try:
                setattr(cfg, f.name, _coerce(env, type(f.default)))
            except (TypeError, ValueError):
                pass


_lock = threading.Lock()
ray_config = RayTpuConfig()
_apply_env_overrides(ray_config)


def apply_system_config(overrides: Optional[Dict[str, Any]]) -> None:
    """``init(_system_config={...})`` hook: named overrides win over env."""
    if not overrides:
        return
    valid = {f.name for f in dataclasses.fields(ray_config)}
    with _lock:
        for key, value in overrides.items():
            if key not in valid:
                raise ValueError(
                    f"unknown _system_config key {key!r}; valid keys: "
                    f"{sorted(valid)}")
            setattr(ray_config, key, value)
