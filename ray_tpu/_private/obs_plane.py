"""Cluster-wide observability plane: node→head event/metric shipping.

Role-equivalent to the reference's GcsTaskManager + OpenCensus metrics
agent plumbing (SURVEY.md: core workers buffer task events and flush
them to the GCS; each node's metrics agent exports to the scrape
endpoint): worker-node processes record task events and fast-path stats
locally, and without shipping the head — where ``ray_tpu.timeline()``,
``export_spans()``, the state API, and the dashboard run — only ever
sees its own process.

Two halves:

- :class:`NodeObsShipper` (node side): a background loop that drains
  the task-event buffer's *delta* (``drain_updates`` — a bounded dirty
  set, not a buffer scan) and snapshots the node's metrics registry,
  shipping both to the head over a **dedicated** RPC connection every
  ``obs_ship_period_s``. Bounded per cycle and entirely off the
  execution hot path: executors only ever touch the event buffer they
  already touched before this module existed.

- :class:`ObsAggregator` (head side): the ``obs_report`` RPC handler.
  Task events merge into a bounded cluster-wide store keyed by task id
  (a terminal update replaces its RUNNING predecessor); metric
  snapshots are kept per node for the dashboard's merged Prometheus
  exposition.

The merge helpers at the bottom are what the user-facing views call:
``cluster_task_events`` feeds timeline/tracing/state,
``export_cluster_prometheus`` feeds the dashboard ``/api/metrics``.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private.task_events import TaskEvent

logger = logging.getLogger(__name__)


class ObsAggregator:
    """Head-side sink for node observability reports."""

    def __init__(self, max_events: Optional[int] = None):
        from ray_tpu._private.config import ray_config

        self._lock = threading.Lock()
        self._max = max_events or ray_config.obs_head_max_events
        # task_id -> TaskEvent, insertion-ordered for oldest-first
        # eviction; updates do NOT move to end (a long-running task's
        # terminal update should not outlive contemporaries forever).
        self._events: "collections.OrderedDict[str, TaskEvent]" = \
            collections.OrderedDict()
        # node_id -> latest metrics-registry snapshot (plain data).
        self._metrics: Dict[str, dict] = {}
        self._reports = 0
        self._events_received = 0
        # Bumped whenever the event store changes — pairs with
        # TaskEventBuffer.mutation_seq as the change fingerprint that
        # lets per-scrape aggregations skip an unchanged merge.
        self._mutations = 0

    # -- RPC handler -----------------------------------------------------

    def report(self, node_id: str, events: Optional[list] = None,
               metrics: Optional[dict] = None,
               stages: Optional[list] = None) -> bool:
        # Stage spans fold head-side: the critical-path engine on the
        # head is where per-route attribution vectors live, and node-
        # born stages (replica execute, LLM engine, object plane) must
        # reach the same accumulator the proxy's finish_request closes.
        if stages:
            try:
                from ray_tpu._private import critical_path

                critical_path.ingest(stages)
            except Exception:
                pass  # malformed frame must not poison event merging
        evs = []
        for d in events or []:
            try:
                evs.append(TaskEvent.from_dict(d))
            except Exception:
                continue  # malformed entry must not poison the frame
        with self._lock:
            self._reports += 1
            self._events_received += len(evs)
            if evs:
                self._mutations += 1
            for ev in evs:
                self._events[ev.task_id] = ev
            while len(self._events) > self._max:
                self._events.popitem(last=False)
            if metrics is not None:
                self._metrics[node_id] = metrics
        return True

    def forget_node(self, node_id: str) -> None:
        """Drop a dead node's metric snapshot (its task events stay —
        history outlives the node that produced it)."""
        with self._lock:
            self._metrics.pop(node_id, None)

    # -- read side -------------------------------------------------------

    @property
    def mutation_seq(self) -> int:
        with self._lock:
            return self._mutations

    def task_events(self) -> List[TaskEvent]:
        with self._lock:
            return list(self._events.values())

    def metrics_snapshots(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._metrics)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"reports": self._reports,
                    "events_received": self._events_received,
                    "events_stored": len(self._events),
                    "nodes_with_metrics": len(self._metrics)}


class NodeObsShipper:
    """Node-side shipping loop. One dedicated connection to the head:
    a multi-KB metrics frame must never head-of-line block the node's
    pooled control RPCs (leases, object reports) behind it."""

    def __init__(self, worker, head_address, node_id: str,
                 stop_event: Optional[threading.Event] = None):
        from ray_tpu._private import perf_stats
        from ray_tpu._private.config import ray_config
        from ray_tpu._private.rpc import RpcClient

        self.worker = worker
        self.node_id = node_id
        self._client = RpcClient.dedicated(tuple(head_address))
        self._stop = stop_event or threading.Event()
        self._period = ray_config.obs_ship_period_s
        self._max_events = ray_config.obs_ship_max_events
        # Metric snapshots ride every Nth cycle (~2s): a fully idle
        # node must not serialize its whole registry twice a second,
        # and metric staleness of a couple seconds is invisible at
        # scrape cadence. Event deltas still ship every cycle.
        self._metrics_every = max(
            1, int(round(2.0 / self._period))) if self._period > 0 else 1
        self._cycle = 0
        self._stat_shipped = perf_stats.counter("obs_shipped_events")
        self._stat_cycles = perf_stats.counter("obs_ship_cycles")
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeObsShipper":
        if self._period <= 0:
            return self  # shipping disabled by config
        # This process's stage records now have a drain: tell the
        # critical-path recorder to queue them (the head never sets
        # this — it folds its own records in place).
        from ray_tpu._private import critical_path

        critical_path.set_shipping(True)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="obs-shipper")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            self.ship_once()
        self.ship_once(final=True)  # terminal states beat the shutdown

    def ship_once(self, final: bool = False) -> bool:
        """One shipping cycle; returns True if a report was sent.
        Never raises — observability must not break the node."""
        try:
            self._cycle += 1
            metrics_cycle = final or self._cycle % self._metrics_every == 0
            events = self.worker.task_events.drain_updates(
                self._max_events)
            # Critical-path stage records ride the same frame (bounded
            # drain; an idle node with no stages pays nothing extra).
            from ray_tpu._private import critical_path

            stages = critical_path.drain_records(self._max_events)
            if not events and not stages and not metrics_cycle:
                return False  # idle between metric beats: no RPC
            metrics = self._snapshot_metrics() if metrics_cycle else None
            try:
                self._client.call("obs_report", node_id=self.node_id,
                                  events=events, metrics=metrics,
                                  stages=stages or None)
            except Exception:
                # Head unreachable / mid-restart: put the drained ids
                # back on the cursor so these events ship next cycle
                # instead of silently vanishing from the cluster view.
                self.worker.task_events.remark_dirty(
                    [d["task_id"] for d in events])
                if stages:
                    critical_path.requeue_records(stages)
                return False
            self._stat_shipped.inc(len(events))
            self._stat_cycles.inc()
            return True
        except Exception:
            return False  # drain/snapshot failure: retry next cycle

    def _snapshot_metrics(self) -> Optional[dict]:
        try:
            from ray_tpu._private.runtime_metrics import (
                collect_runtime_metrics,
            )
            from ray_tpu.util.metrics import snapshot_registry

            # Fold runtime gauges + fast-path stats into the registry
            # first, so the shipped snapshot is the same view a local
            # scrape would get.
            collect_runtime_metrics()
            return snapshot_registry()
        except Exception:
            return None

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2 * self._period + 1.0)
        else:
            self.ship_once(final=True)
        self._client.close()


# -- cluster-wide merge helpers ---------------------------------------------


def _prefer(a: TaskEvent, b: TaskEvent) -> TaskEvent:
    """Duplicate task_id (e.g. a task re-executed after node death):
    prefer the terminal record, then the later-ending one."""
    a_done, b_done = a.end_s is not None, b.end_s is not None
    if a_done != b_done:
        return a if a_done else b
    if a_done and b_done:
        return a if a.end_s >= b.end_s else b
    return a if a.start_s >= b.start_s else b


def cluster_task_events(worker, sort: bool = True) -> List[TaskEvent]:
    """Every task event this process can see: its own buffer plus — on
    the cluster head — the aggregator's node-shipped events, deduped by
    task id and ordered by start time. Aggregating callers that only
    fold counts (the per-job metric collection, ``job_summary``) pass
    ``sort=False``: the sort is the O(n log n) term on a walk that runs
    every scrape/ship cycle, and order is irrelevant to them."""
    buf = getattr(worker, "task_events", None)
    local = buf.snapshot() if buf is not None else []  # thin client
    head = getattr(worker, "cluster_head", None)
    agg = getattr(head, "obs", None) if head is not None else None
    if agg is None:
        return local
    merged: Dict[str, TaskEvent] = {ev.task_id: ev for ev in local}
    for ev in agg.task_events():
        cur = merged.get(ev.task_id)
        merged[ev.task_id] = ev if cur is None else _prefer(ev, cur)
    out = list(merged.values())
    if sort:
        out.sort(key=lambda ev: ev.start_s)
    return out


def export_cluster_prometheus(worker) -> str:
    """One Prometheus exposition for the whole cluster: the head's own
    registry (runtime gauges refreshed first — the docstring contract
    of `_private/runtime_metrics.py`) merged with every node's shipped
    snapshot, node series tagged ``node="<node_id>"``."""
    from ray_tpu._private.runtime_metrics import collect_runtime_metrics
    from ray_tpu.util.metrics import render_prometheus, snapshot_registry

    try:
        collect_runtime_metrics()
    except Exception:  # noqa: BLE001 — user metrics still export
        pass
    snaps: List[Any] = [(snapshot_registry(), None)]
    head = getattr(worker, "cluster_head", None)
    agg = getattr(head, "obs", None) if head is not None else None
    if agg is not None:
        for node_id, snap in sorted(agg.metrics_snapshots().items()):
            snaps.append((snap, {"node": node_id}))
    return render_prometheus(snaps)
