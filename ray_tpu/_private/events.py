"""Structured cluster events.

Reference: `src/ray/util/event.h` + the dashboard event module — notable
state transitions (node up/down, autoscaling decisions, serve deploys,
job state changes) land in a bounded in-memory buffer the dashboard and
state API serve, so "what happened to the cluster" has one answer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_BUFFER_MAX = 2000
_lock = threading.Lock()
_events: "deque[Dict[str, Any]]" = deque(maxlen=_BUFFER_MAX)
_counter = [0]
# Cluster-node processes forward events to the HEAD's buffer (which the
# dashboard and gcs_events serve) — a process-local buffer on a worker
# node is invisible to observers. Set by NodeRuntime at bring-up.
_forwarder = [None]


def set_forwarder(fn) -> None:
    _forwarder[0] = fn


def record_event(source: str, message: str, *,
                 severity: str = "INFO", **metadata) -> None:
    """Append one event (and forward to the head when this process is a
    cluster node); never raises — observability must not break the path
    it observes."""
    try:
        with _lock:
            _counter[0] += 1
            _events.append({
                "event_id": _counter[0],
                "timestamp": time.time(),
                "source": source,
                "severity": severity,
                "message": message,
                **({"metadata": metadata} if metadata else {}),
            })
        fwd = _forwarder[0]
        if fwd is not None:
            fwd(source=source, message=message, severity=severity,
                metadata=metadata or None)
    except Exception:
        pass


def list_events(limit: int = 200,
                source: Optional[str] = None) -> List[Dict[str, Any]]:
    with _lock:
        items = list(_events)
    if source is not None:
        items = [e for e in items if e["source"] == source]
    return items[-limit:]


def clear_events() -> None:
    with _lock:
        _events.clear()
