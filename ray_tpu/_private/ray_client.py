"""Ray-client mode: a thin remote driver proxying the core API.

Reference: `python/ray/util/client/` (`ray://` — a client-side worker
forwards API calls over gRPC to a server that translates them into real
Ray calls, `util/client/server/server.py`). Here:

- `ClientServer` runs inside a real driver process (usually the cluster
  head driver) and executes submits/gets/puts on its behalf through the
  normal worker — specs are the wire currency, so tasks, actors, named
  actors, and nested ObjectRefs all work unchanged.
- `ClientWorker` replaces the in-process runtime on the client:
  `ray_tpu.init(address="host:port")` connects it, and the public API
  (remote/get/put/wait/kill/cancel/get_actor) proxies transparently.
- The server pins every object the client holds a handle to (its
  `ObjectRef`s are entries in the server-side registry) and drops pins
  as the client's handles are GC'd (client_free) or the client
  disconnects.

Scope: the core task/actor/object API. Library layers (data/train/...)
run fine on a client for driving-side logic; state/dashboard APIs stay
server-side.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Tuple

from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.rpc import RpcClient, RpcServer

CLIENT_SERVER_METHODS = frozenset({
    "client_submit", "client_put", "client_get", "client_wait",
    "client_free", "client_kill", "client_cancel",
    "client_get_named_actor", "client_register_named_actor",
    "client_remove_named_actor",
})


class ClientServer:
    """Hosted by a real driver: executes client calls via its worker."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        from ray_tpu._private import worker as worker_mod

        self._worker = worker_mod.global_worker()
        # Pins: every object a client holds a handle to stays registered
        # here (oid -> ObjectRef) so cluster release can't free it.
        self._pins: Dict[bytes, Any] = {}
        self._lock = threading.Lock()
        self.server = RpcServer({
            "client_submit": self._submit,
            "client_put": self._put,
            "client_get": self._get,
            "client_wait": self._wait,
            "client_free": self._free,
            "client_kill": self._kill,
            "client_cancel": self._cancel,
            "client_get_named_actor": self._get_named,
            "client_register_named_actor": self._register_named,
            "client_remove_named_actor": self._remove_named,
        }, host=host, port=port,
           dedupe_methods=frozenset({"client_submit"}))
        self.address: Tuple[str, int] = self.server.address

    def _pin(self, oid_bytes: bytes):
        from ray_tpu.object_ref import ObjectRef

        with self._lock:
            if oid_bytes not in self._pins:
                self._pins[oid_bytes] = ObjectRef(ObjectID(oid_bytes))

    def _submit(self, spec):
        # Deserializing the spec registered any contained ObjectRefs
        # with this worker (borrow semantics). Return ids were assigned
        # client-side; pin them here on the client's behalf.
        self._worker.backend.submit(spec)
        for oid in spec.return_ids:
            self._pin(oid.binary())
        return True

    def _put(self, value):
        ref = self._worker.put_object(value)
        self._pin(ref.binary())
        return ref.binary()

    def _get(self, oids: List[bytes], timeout):
        from ray_tpu import exceptions as exc
        from ray_tpu.object_ref import ObjectRef

        refs = [ObjectRef(ObjectID(o)) for o in oids]
        try:
            values = self._worker.get_objects(refs, timeout)
            # A generator value carries refs the client will now hold
            # handles to; pin them so a client-side free of the generator
            # alone can't drop yielded objects the client still uses.
            from ray_tpu.object_ref import ObjectRefGenerator

            for v in values:
                if isinstance(v, ObjectRefGenerator):
                    for r in v:
                        self._pin(r.binary())
            return {"values": values}
        except exc.GetTimeoutError:
            # Slice timeout: the client long-polls in bounded slices (a
            # single blocking RPC would trip the socket timeout on slow
            # tasks) and distinguishes its own deadline from ours.
            return {"pending": True}
        except Exception as e:  # noqa: BLE001 — shipped to the client
            return {"error": e}

    def _wait(self, oids: List[bytes], num_returns, timeout):
        from ray_tpu.object_ref import ObjectRef

        refs = [ObjectRef(ObjectID(o)) for o in oids]
        ready, not_ready = self._worker.wait(refs, num_returns, timeout)
        return ([r.binary() for r in ready],
                [r.binary() for r in not_ready])

    def _free(self, oids: List[bytes]):
        with self._lock:
            for o in oids:
                self._pins.pop(o, None)
        return True

    def _kill(self, actor_id: bytes, no_restart: bool):
        aid = ActorID(actor_id)
        self._worker.gcs.remove_named_actor_by_id(aid)
        self._worker.backend.kill_actor(aid, no_restart)
        return True

    def _cancel(self, task_id):
        self._worker.backend.cancel(task_id)
        return True

    def _get_named(self, name: str, namespace):
        return self._worker.gcs.get_named_actor(name, namespace)

    def _register_named(self, name: str, namespace, handle):
        self._worker.gcs.register_named_actor(name, namespace, handle)
        return True

    def _remove_named(self, actor_id: bytes):
        self._worker.gcs.remove_named_actor_by_id(ActorID(actor_id))
        return True

    def shutdown(self):
        self.server.shutdown()


def enable_client_server(host: str = "0.0.0.0",
                         port: int = 0) -> ClientServer:
    """Start serving remote clients from this driver process."""
    return ClientServer(host, port)


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class _ClientBackend:
    """Minimal backend surface for a proxy worker."""

    def __init__(self, worker: "ClientWorker"):
        self._worker = worker

    def submit(self, spec):
        self._worker._rpc.call("client_submit", spec=spec)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._worker._rpc.call("client_kill",
                               actor_id=actor_id.binary(),
                               no_restart=no_restart)

    def cancel(self, task_id):
        self._worker._rpc.call("client_cancel", task_id=task_id)

    def notify_blocked(self):
        pass

    def notify_unblocked(self):
        pass

    def shutdown(self):
        pass


class _ClientGCS:
    def __init__(self, worker: "ClientWorker"):
        self._worker = worker

    def get_named_actor(self, name: str, namespace=None):
        from ray_tpu._private.rpc import RemoteCallError

        try:
            return self._worker._rpc.call(
                "client_get_named_actor", name=name, namespace=namespace)
        except RemoteCallError as e:
            raise ValueError(str(e)) from None

    def register_named_actor(self, name: str, namespace, handle):
        self._worker._rpc.call("client_register_named_actor", name=name,
                               namespace=namespace, handle=handle)

    def remove_named_actor_by_id(self, actor_id: ActorID):
        self._worker._rpc.call("client_remove_named_actor",
                               actor_id=actor_id.binary())


class ClientWorker:
    """Drop-in for Worker on a thin client: public-API calls proxy to
    the ClientServer. Reuses Worker's spec-building path (submit assigns
    return ids locally; the server honours them)."""

    is_client = True

    # Long-poll slice: each blocking server call is bounded well below
    # the transport's 30s socket timeout.
    _POLL_SLICE_S = 10.0

    def __init__(self, address: Tuple[str, int]):
        import queue as _queue

        from ray_tpu._private.ids import JobID, TaskID, WorkerID
        from ray_tpu._private.worker import _TaskContext

        self._rpc = RpcClient.dedicated(tuple(address))
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self.namespace = f"client-{self.job_id.hex()}"
        self.task_context = _TaskContext()
        self._driver_task_id = TaskID.from_random()
        self.shm_plane = None
        self.backend = _ClientBackend(self)
        self.gcs = _ClientGCS(self)
        self._free_lock = threading.Lock()
        self._handle_counts: Dict[bytes, int] = {}
        # Frees ride a background thread: __del__ can fire from a GC
        # pass INSIDE an in-flight RPC on this same thread, and a
        # synchronous free would self-deadlock on the client lock.
        self._free_q: "_queue.Queue" = _queue.Queue()
        self._free_rpc = RpcClient.dedicated(tuple(address))
        self._free_thread = threading.Thread(
            target=self._free_loop, daemon=True, name="client-free")
        self._free_thread.start()

    def _free_loop(self):
        import queue as _queue

        while True:
            batch = [self._free_q.get()]
            while True:
                try:
                    batch.append(self._free_q.get_nowait())
                except _queue.Empty:
                    break
            try:
                self._free_rpc.call("client_free", oids=batch)
            except Exception:  # noqa: BLE001 — disconnect is fine
                pass

    # -- object API ------------------------------------------------------

    def put_object(self, value):
        from ray_tpu.object_ref import ObjectRef

        oid_bytes = self._rpc.call("client_put", value=value)
        return ObjectRef(ObjectID(oid_bytes))

    def get_objects(self, refs, timeout=None):
        import time as _time

        from ray_tpu import exceptions as exc

        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        oids = [r.binary() for r in refs]
        while True:
            remaining = None if deadline is None \
                else max(0.0, deadline - _time.monotonic())
            slice_t = self._POLL_SLICE_S if remaining is None \
                else min(remaining, self._POLL_SLICE_S)
            out = self._rpc.call("client_get", oids=oids,
                                 timeout=slice_t)
            if "error" in out:
                raise out["error"]
            if "values" in out:
                return out["values"]
            # pending: server slice elapsed — our own deadline?
            if remaining is not None and remaining <= slice_t:
                raise exc.GetTimeoutError(
                    f"get() timed out after {timeout}s (client mode)")

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        import time as _time

        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        by_id = {r.binary(): r for r in refs}
        while True:
            remaining = None if deadline is None \
                else max(0.0, deadline - _time.monotonic())
            slice_t = self._POLL_SLICE_S if remaining is None \
                else min(remaining, self._POLL_SLICE_S)
            ready_b, not_ready_b = self._rpc.call(
                "client_wait", oids=list(by_id),
                num_returns=num_returns, timeout=slice_t)
            enough = len(ready_b) >= num_returns
            out_of_time = remaining is not None and remaining <= slice_t
            if enough or out_of_time:
                return ([by_id[b] for b in ready_b],
                        [by_id[b] for b in not_ready_b])

    # -- task API --------------------------------------------------------

    def submit(self, spec):
        from ray_tpu.object_ref import ObjectRef

        # Shared return-id semantics live on TaskSpec (dynamic → one
        # generator ref; the server pins the yielded refs when it ships
        # the generator back, see ClientServer._get).
        refs = [ObjectRef(oid) for oid in spec.assign_return_ids()]
        self.backend.submit(spec)
        return refs

    def current_task_id(self):
        return self._driver_task_id

    # -- handle refcounting: last local handle frees the server pin -----

    def register_object_ref(self, ref) -> int:
        with self._free_lock:
            b = ref.binary()
            self._handle_counts[b] = self._handle_counts.get(b, 0) + 1
            return self._handle_counts[b]

    def unregister_object_ref(self, oid: ObjectID) -> bool:
        with self._free_lock:
            b = oid.binary()
            n = self._handle_counts.get(b, 0) - 1
            if n > 0:
                self._handle_counts[b] = n
                return False
            self._handle_counts.pop(b, None)
        self._free_q.put(b)  # background thread RPCs (GC-safe)
        return True

    def shutdown(self):
        try:
            self._rpc.close()
            self._free_rpc.close()
        except Exception:  # noqa: BLE001
            pass
