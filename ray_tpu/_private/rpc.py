"""Framed TCP RPC: the cluster control/data plane transport.

Role-equivalent to the reference's gRPC layer (`src/ray/rpc/`): a threaded
server dispatching named methods, and a client with pooled connections.
The envelope and all standard-typed payloads ride the typed wire format
(`_private/wire.py` — the protobuf-contracts role: declared, versioned
`Request`/`Reply` messages, validated at decode); only user payloads
(functions, custom objects) are carried as explicitly-tagged opaque
(cloudpickle) sections. On TPU-VM fleets the control plane rides DCN and
this framing is sufficient; the tensor plane never touches it (XLA
collectives own ICI).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private import sanitize_hooks, wire
from ray_tpu._private.config import ray_config

# Cap on the server-side TLS handshake so one stalled/half-open peer can
# only pin its own connection thread, never the accept loop.
_TLS_HANDSHAKE_TIMEOUT_S = 10.0

_LEN = struct.Struct("!I")
# Reply retention is per client (keyed by the client's id prefix), not a
# global FIFO: a request with sequence N implicitly acks every reply with
# sequence < N from that client (the client holds a lock across each
# call+retry), so each client retains at most its in-flight reply. The
# only global bound needed is on the number of distinct clients.
_MAX_CLIENT_CACHES = 4096


def _tls_context(server: bool):
    """Mutual-TLS context when `use_tls` is configured (reference:
    RAY_USE_TLS + RAY_TLS_* in rpc/grpc_server); None = plaintext."""
    from ray_tpu._private.config import ray_config

    if not ray_config.use_tls:
        return None
    import ssl

    if not (ray_config.tls_server_cert and ray_config.tls_server_key
            and ray_config.tls_ca_cert):
        raise ValueError("use_tls requires tls_server_cert, "
                         "tls_server_key, and tls_ca_cert")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER if server
                         else ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(ray_config.tls_server_cert,
                        ray_config.tls_server_key)
    ctx.load_verify_locations(ray_config.tls_ca_cert)
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = False  # fleet nodes verify by CA, not hostname
    return ctx


def routable_host(peer_address: Tuple[str, int]) -> str:
    """The local interface IP a peer at ``peer_address`` would reach us
    on (UDP-connect trick — the kernel picks the outbound interface; no
    packet is sent). Nodes advertise this instead of loopback so object
    and control endpoints work across hosts; falls back to loopback."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((peer_address[0], peer_address[1] or 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class FrameTooLarge(wire.WireError):
    """Frame length prefix exceeds ``rpc_max_frame_bytes``. Raised
    BEFORE the body is read or its buffer allocated; the stream cannot
    be resynchronized past the unread body, so the connection must be
    dropped (unlike other :class:`wire.WireError` rejections, which
    leave the frame boundary intact)."""


def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = wire.encode(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    cap = ray_config.rpc_max_frame_bytes
    if length > cap:
        raise FrameTooLarge(
            f"frame of {length} bytes exceeds rpc_max_frame_bytes="
            f"{cap}")
    return wire.decode(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


class RpcServer:
    """Threaded request/response server: {method, kwargs} → {ok, result}.

    Methods listed in ``dedupe_methods`` get exactly-once semantics under
    client retry: completed replies are retained per client until that
    client's next request acks them (request seq N acks replies < N), and
    a retry racing a still-running execution waits for that execution
    instead of starting a second one. A waiter that finds the reply gone
    (client cache evicted) gets an error reply — never a re-execution.
    Idempotent methods skip the cache so large replies (e.g. object
    payloads) aren't retained.
    """

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0,
                 dedupe_methods: Optional[frozenset] = None):
        server_self = self
        self._conns: set = set()
        self._conns_lock = threading.Lock()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                if tls_ctx is not None:
                    # Handshake here, in the per-connection thread — never
                    # in get_request(), where a half-open peer would wedge
                    # the single accept loop for every node. A bounded
                    # timeout caps how long a stalled handshake can hold
                    # this thread. wrap_socket() detaches the raw socket,
                    # so socketserver's shutdown_request() no longer
                    # reaches the real fd — close the wrapped socket
                    # ourselves in finish().
                    try:
                        self.request.settimeout(_TLS_HANDSHAKE_TIMEOUT_S)
                        self.request = tls_ctx.wrap_socket(
                            self.request, server_side=True)
                        self.request.settimeout(None)
                    except (OSError, ValueError):  # SSLError is OSError
                        return
                with server_self._conns_lock:
                    server_self._conns.add(self.request)
                try:
                    self._serve_requests()
                except sanitize_hooks.SimulatedCrash:
                    # Injected death mid-handler: the connection drops
                    # without a reply, exactly like the process dying —
                    # never an ok=False Reply (the catch-all below must
                    # not convert a simulated crash into a handled
                    # application error, or crash-fault exploration
                    # would silently explore nothing).
                    return

            def _serve_requests(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except FrameTooLarge as e:
                        # The body was never read: the stream is
                        # desynced, so reject loudly and drop the
                        # connection (best-effort reply — the peer may
                        # be gone already).
                        self._reject(str(e))
                        return
                    except wire.WireError as e:
                        # The frame was length-delimited and fully
                        # consumed before decode failed, so the stream
                        # is still aligned: a skewed peer (unknown
                        # message type, future schema version,
                        # malformed body) degrades to a clean
                        # per-message rejection, never a dead
                        # connection.
                        if not self._reject(str(e)):
                            return
                        continue
                    except (ConnectionError, OSError):
                        return
                    if not isinstance(msg, wire.Request):
                        # Typed-envelope violation: same frame-aligned
                        # rejection as a decode failure above.
                        if not self._reject(
                                "expected rpc.Request envelope, got "
                                + type(msg).__name__):
                            return
                        continue
                    rid = msg.id or None
                    if msg.method not in server_self.dedupe_methods:
                        rid = None
                    reply = server_self._await_reply(
                        rid, getattr(msg, "ack", -2)) if rid else None
                    if reply is None:
                        t0 = time.perf_counter()
                        try:
                            # Yield point on the execute side of the
                            # dedupe decision: a connection death lands
                            # either before this crossing (request
                            # never ran — the rid resubmit executes it
                            # once) or between here and
                            # `rpc.server.reply` (it ran, the reply is
                            # cached — the resubmit must get the cache,
                            # never a second execution). INSIDE the try
                            # so a crash injected at the crossing
                            # itself tombstones the in-flight claim
                            # taken just above — stranding it would
                            # hang every retry under this rid.
                            sanitize_hooks.sched_point(
                                "rpc.server.dispatch")
                            fn = server_self.handlers[msg.method]
                            result = fn(**(msg.kwargs or {}))
                            reply = wire.Reply(ok=True, result=result)
                        except sanitize_hooks.SimulatedCrash as e:
                            # Tombstone the claim before dying: the
                            # PROCESS survived this injected death, so
                            # its dedupe contract must too — a retry
                            # under this rid gets a failure reply,
                            # never a second execution (releasing the
                            # claim instead let the client's built-in
                            # retry double-execute the handler), and
                            # any parked waiter wakes instead of
                            # hanging on the in-flight event.
                            server_self._finish_reply(rid, wire.Reply(
                                ok=False, error=f"SimulatedCrash: {e}"))
                            raise
                        except BaseException as e:  # noqa: BLE001
                            import traceback

                            reply = wire.Reply(
                                ok=False,
                                error=f"{type(e).__name__}: {e}",
                                traceback=traceback.format_exc())
                        server_self._record_handler(
                            msg.method, time.perf_counter() - t0,
                            ok=reply.ok)
                        server_self._finish_reply(rid, reply)
                    sanitize_hooks.sched_point("rpc.server.reply")
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        return

            def _reject(self, detail: str) -> bool:
                """Send the typed wire-rejection reply; False = the
                peer is unreachable (caller should stop serving)."""
                try:
                    send_msg(self.request,
                             wire.Reply(ok=False, error=f"wire: {detail}"))
                    return True
                except (ConnectionError, OSError):
                    return False

            def finish(self):
                with server_self._conns_lock:
                    server_self._conns.discard(self.request)
                if tls_ctx is not None:
                    # self.request is the SSL-wrapped socket (or the raw
                    # one if the handshake failed); closing it sends
                    # close_notify and releases the detached fd that
                    # socketserver's shutdown_request can no longer see.
                    try:
                        self.request.close()
                    except OSError:
                        pass

        tls_ctx = _tls_context(server=True)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

            # NB: no get_request() override — the TLS handshake must not
            # run in the accept thread (see Handler.handle above).

        self.handlers = handlers
        self.dedupe_methods = dedupe_methods or frozenset()
        # client id prefix → {seq: reply}; OrderedDict for LRU over clients.
        self._replies: OrderedDict[str, Dict[int, Any]] = OrderedDict()
        self._inflight: Dict[str, threading.Event] = {}
        self._replies_lock = threading.Lock()
        self._handler_stats: Dict[str, Dict[str, float]] = {}
        self._stats_lock = threading.Lock()
        self._server = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"rpc-server-{self.address[1]}")
        self._thread.start()

    def add_handler(self, name: str, fn: Callable):
        self.handlers[name] = fn

    # -- per-handler stats (reference: instrumented_io_context +
    # event_stats — per-handler latency visibility on control loops) ----

    def _record_handler(self, method: str, seconds: float, ok: bool):
        with self._stats_lock:
            st = self._handler_stats.setdefault(
                method, {"calls": 0, "errors": 0, "total_s": 0.0,
                         "max_s": 0.0})
            st["calls"] += 1
            if not ok:
                st["errors"] += 1
            st["total_s"] += seconds
            st["max_s"] = max(st["max_s"], seconds)

    def handler_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-method call counts and latency aggregates."""
        with self._stats_lock:
            out = {}
            for method, st in self._handler_stats.items():
                mean = st["total_s"] / st["calls"] if st["calls"] else 0
                out[method] = {
                    "calls": st["calls"], "errors": st["errors"],
                    "mean_ms": round(mean * 1e3, 3),
                    "max_ms": round(st["max_s"] * 1e3, 3),
                    "total_s": round(st["total_s"], 3),
                }
            return out

    @staticmethod
    def _split_rid(rid: str) -> Tuple[str, int]:
        prefix, _, seq = rid.rpartition(":")
        return prefix, int(seq)

    def _await_reply(self, rid: str, ack: int = -2):
        """Cached reply for rid, waiting out an in-flight execution."""
        prefix, seq = self._split_rid(rid)
        with self._replies_lock:
            per_client = self._replies.get(prefix)
            if per_client is not None:
                cached = per_client.get(seq)
                if cached is not None:
                    return cached
                # Purge replies the client has CONSUMED. A serialized
                # client (one call in flight, ack absent) implicitly
                # acks seq-1; a pipelined client has many outstanding,
                # so it declares its consumed watermark explicitly —
                # purging on "saw seq N" would evict replies still on
                # the wire and break resubmit dedupe.
                consumed_below = seq if ack == -2 else ack + 1
                for old in [s for s in per_client if s < consumed_below]:
                    del per_client[old]
            event = self._inflight.get(rid)
            if event is None:
                # First sighting: claim the id; caller executes.
                self._inflight[rid] = threading.Event()
                return None
        event.wait()
        with self._replies_lock:
            reply = self._replies.get(prefix, {}).get(seq)
        if reply is None:
            # Cache evicted between finish and wakeup: fail the retry
            # rather than silently executing a second time.
            return wire.Reply(
                ok=False,
                error="RetryError: reply for retried request expired "
                      "before delivery")
        return reply

    def _finish_reply(self, rid: Optional[str], reply: Any):
        if rid is None:
            return
        prefix, seq = self._split_rid(rid)
        with self._replies_lock:
            per_client = self._replies.setdefault(prefix, {})
            per_client[seq] = reply
            self._replies.move_to_end(prefix)
            while len(self._replies) > _MAX_CLIENT_CACHES:
                self._replies.popitem(last=False)
            event = self._inflight.pop(rid, None)
        if event is not None:
            event.set()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        # Close established connections too — a dead server process
        # would; leaving them open strands clients in 30s recv timeouts
        # instead of the fast reconnect a restarted peer needs.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class RpcClient:
    """One logical connection per target address, thread-safe via a lock
    per connection (requests are small; head fan-in is the bottleneck long
    before this is)."""

    _pools: Dict[Tuple[str, int], "RpcClient"] = {}
    _pools_lock = threading.Lock()

    def __init__(self, address: Tuple[str, int]):
        self.address = tuple(address)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._id_prefix = uuid.uuid4().hex[:12]
        self._seq = 0

    @classmethod
    def to(cls, address) -> "RpcClient":
        key = tuple(address)
        with cls._pools_lock:
            client = cls._pools.get(key)
            if client is None:
                client = cls(key)
                cls._pools[key] = client
            return client

    @classmethod
    def dedicated(cls, address) -> "RpcClient":
        """A non-pooled client with its own connection. Required for
        long-poll calls (pubsub subscribe): the pooled client serializes
        calls on one socket, so a 10s poll would head-of-line block every
        other RPC this process sends to the same address."""
        return cls(tuple(address))

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(self.address, timeout=30)
            ctx = _tls_context(server=False)
            if ctx is not None:
                sock = ctx.wrap_socket(sock)
            self._sock = sock
        return self._sock

    def call_with_rid(self, rid: str, method: str, **kwargs) -> Any:
        """Issue a request under a CALLER-chosen request id — the
        resubmit path for pipelined sends: the node's dedupe cache keys
        on the id, so a retry of an un-acked pipelined request cannot
        execute twice."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    sock = self._ensure()  # raylint: disable=R2 -- per-connection request/reply serialization IS this client's design: one in-flight call per socket, callers needing concurrency use dedicated/pipelined clients
                    send_msg(sock, wire.Request(id=rid, method=method,  # raylint: disable=R2 -- see above: the lock IS the request/reply framing discipline for this socket
                                                kwargs=kwargs))
                    reply = recv_msg(sock)  # raylint: disable=R2 -- see above: reply must be read under the same hold that sent the request (TCP ordering is the match)
                    break
                except wire.WireError as e:
                    # Off-protocol reply frame: drop the socket and
                    # surface typed — never a silent retry (the
                    # request may have executed).
                    self.close_locked()
                    raise RemoteCallError(
                        f"{method} on {self.address}: malformed "
                        f"reply: {e}") from None
                except (ConnectionError, OSError):
                    self.close_locked()
                    if attempt:
                        raise
        if not isinstance(reply, wire.Reply):
            raise RemoteCallError(
                f"{method} on {self.address}: malformed reply "
                f"{type(reply).__name__}")
        if not reply.ok:
            raise RemoteCallError(
                f"{method} failed on {self.address}: {reply.error}\n"
                + (reply.traceback or ""))
        return reply.result

    def call(self, method: str, **kwargs) -> Any:
        with self._lock:
            self._seq += 1
            rid = f"{self._id_prefix}:{self._seq}"
            for attempt in (0, 1):
                try:
                    sock = self._ensure()  # raylint: disable=R2 -- per-connection request/reply serialization IS this client's design: one in-flight call per socket, callers needing concurrency use dedicated/pipelined clients
                    send_msg(sock, wire.Request(id=rid, method=method,  # raylint: disable=R2 -- see above: the lock IS the request/reply framing discipline for this socket
                                                kwargs=kwargs))
                    reply = recv_msg(sock)  # raylint: disable=R2 -- see above: reply must be read under the same hold that sent the request (TCP ordering is the match)
                    break
                except wire.WireError as e:
                    # Same typed rejection as call_with_rid above.
                    self.close_locked()
                    raise RemoteCallError(
                        f"{method} on {self.address}: malformed "
                        f"reply: {e}") from None
                except (ConnectionError, OSError):
                    self.close_locked()
                    if attempt:
                        raise
        if not isinstance(reply, wire.Reply):
            raise RemoteCallError(
                f"{method} on {self.address}: malformed reply "
                f"{type(reply).__name__}")
        if not reply.ok:
            raise RemoteCallError(
                f"{method} failed on {self.address}: {reply.error}\n"
                + (reply.traceback or ""))
        return reply.result

    def close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self.close_locked()


class RemoteCallError(RuntimeError):
    pass


class LruTable:
    """Tiny bounded LRU mapping for the interned-template protocol's two
    ends (the head's per-node claim set, the node's template cache).
    Both sides see the same ordered stream of register/reference events
    over one pipelined channel and use the same touch discipline, so —
    with the receiver sized LARGER than the claimer — a claimed id is
    present on the receiver; a claim evicted here is simply re-shipped."""

    __slots__ = ("_d", "_cap")

    def __init__(self, capacity: int):
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._cap = capacity

    def __contains__(self, key) -> bool:
        if key in self._d:
            self._d.move_to_end(key)
            return True
        return False

    def get(self, key, default=None):
        if key in self._d:
            self._d.move_to_end(key)
            return self._d[key]
        return default

    def add(self, key, value=True) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self._cap:
            self._d.popitem(last=False)

    def discard(self, key) -> None:
        self._d.pop(key, None)

    def __len__(self) -> int:
        return len(self._d)


def batched_object_read(get_object: Callable, oids, timeout: float = 30.0):
    """Shared server-side loop for get_objects_batch handlers (head and
    node expose the same RPC): one deadline covers the whole set;
    ``get_object(oid, remaining) -> (ok, value, error)`` is the
    per-object read."""
    deadline = time.monotonic() + timeout
    out = []
    for oid in oids:
        remaining = max(0.0, deadline - time.monotonic())
        out.append(list(get_object(oid, remaining)))
    return out


class CoalescingBatcher:
    """Group-commit frontend for a streaming channel: producers append
    items without blocking (until the bounded queue fills — the
    backpressure boundary); a flusher thread drains EVERYTHING
    accumulated per cycle into one frame via ``send_frame(items)``.

    There is deliberately no timer: an idle channel's first item
    flushes immediately, and while a frame is being serialized/sent
    (or the peer's socket pushes back), new items pile up and ride the
    next frame — the busier the channel, the bigger the batches
    (flush-on-idle group commit, the reference's submission-pipelining
    shape). ``send_frame`` must handle its own failures; an exception
    it raises is routed to ``on_error(items, exc)`` and never kills the
    flusher. NB items are handed to send_frame strictly in add order,
    but a caller needing cross-CHANNEL ordering (e.g. a synchronous RPC
    that must observe prior submissions) must ``flush()`` first."""

    def __init__(self, send_frame: Callable, name: str = "batcher",
                 on_error: Optional[Callable] = None,
                 max_items_per_frame: int = 1024,
                 capacity: int = 16384):
        from ray_tpu._private import perf_stats

        self._send_frame = send_frame
        self._on_error = on_error
        self._max_items = max_items_per_frame
        self._capacity = capacity
        self._items: list = []
        self._cond = threading.Condition()
        self._in_flight = 0          # frames currently being sent
        self._closed = False
        # Fast-path observability: queue delay is stamped once per
        # empty→nonempty transition (not per add — one branch on the
        # hot path), measured when the flusher drains; flush size and a
        # stall counter ride the same drain. Global stats, not
        # per-batcher: cardinality stays bounded under node churn.
        self._first_enq = 0.0
        self._stat_delay = perf_stats.latency("batcher_queue_delay_seconds")
        self._stat_flush = perf_stats.dist("batcher_flush_items")
        self._stat_stalls = perf_stats.counter("batcher_backpressure_stalls")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"rpc-batch-{name}")
        self._thread.start()

    def add(self, item: Any) -> None:
        sanitize_hooks.sched_point("rpc.batcher.add")
        with self._cond:
            if self._closed:
                raise ConnectionError("batcher closed")
            while len(self._items) >= self._capacity:
                self._stat_stalls.inc()
                self._cond.wait(1.0)  # backpressure: queue at capacity
                if self._closed:
                    raise ConnectionError("batcher closed")
            if not self._items:
                self._first_enq = time.monotonic()
            self._items.append(item)
            self._cond.notify_all()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._closed:
                    self._cond.wait()
                if self._closed and not self._items:
                    return  # drained: flusher retires
                batch = self._items[:self._max_items]
                del self._items[:self._max_items]
                now = time.monotonic()
                self._stat_delay.record(now - self._first_enq)
                self._stat_flush.record(len(batch))
                if self._items:
                    # Partial drain: the residue's true first-enqueue is
                    # unknown — restamp now (the delay stat under-reads
                    # by at most one drain cycle, acceptable for a
                    # monitoring distribution).
                    self._first_enq = now
                self._in_flight += 1
                self._cond.notify_all()
            # Deterministic-schedule seam: the drained-but-unsent window
            # (items are out of the queue, the frame not yet on the
            # wire) is the batcher's racy boundary.
            sanitize_hooks.sched_point("rpc.batcher.flush")
            try:
                self._send_frame(batch)
            except sanitize_hooks.SimulatedCrash:
                # Injected death mid-frame: the flusher dies with the
                # "process" — routing it into on_error would convert a
                # simulated crash into a handled send failure.
                raise
            except BaseException as e:  # noqa: BLE001 — surfaced per batch
                if self._on_error is not None:
                    try:
                        self._on_error(batch, e)
                    except Exception:
                        pass
            finally:
                with self._cond:
                    self._in_flight -= 1
                    self._cond.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every added item has been handed to send_frame
        AND those frames' sends returned (not necessarily acknowledged
        by the peer — see the underlying channel's own flush for that)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._items or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    @property
    def backlog(self) -> int:
        with self._cond:
            return len(self._items)

    def close(self, drain_timeout: float = 0.0) -> None:
        """Stop accepting items; the flusher drains what was already
        added, then retires (a dropped channel must not leak one parked
        thread per reconnect cycle).

        ``drain_timeout > 0`` additionally blocks (via :meth:`flush`)
        until every already-added item has been handed to send_frame
        and those sends returned — the shutdown/failover-boundary form,
        so a group-committed batch cannot die buffered. The default
        non-blocking form is for failure paths that may run ON the
        flusher thread itself (where waiting on our own in-flight send
        could only time out)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if drain_timeout > 0:
            self.flush(drain_timeout)


class PipelinedClient:
    """Streaming request channel: callers enqueue requests WITHOUT
    waiting for replies; a reader thread drains them in order and hands
    failures to a callback. This is the lease-pipelining transport
    (reference: `direct_task_transport.h:75` — once a worker lease is
    held, tasks stream to it without per-task round trips; errors come
    back asynchronously).

    One instance per (submitter, target) pair, own socket — never the
    pooled request/reply connection. TCP ordering gives reply->request
    matching by sequence.
    """

    def __init__(self, address: Tuple[str, int],
                 on_error: Optional[
                     Callable[[Any, str, str, bool], None]] = None):
        """on_error(tag, message, rid, connection_lost) fires from the
        reader thread for failure replies and for requests left un-acked
        when the connection drops."""
        self.address = tuple(address)
        self._on_error = on_error
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._pending: "OrderedDict[int, Any]" = OrderedDict()
        self._pending_lock = threading.Lock()
        self._seq = 0
        self._acked = -1  # highest seq whose reply we have consumed
        self._id_prefix = uuid.uuid4().hex[:12]
        self._closed = threading.Event()
        self._reader: Optional[threading.Thread] = None
        self._drained = threading.Condition(self._pending_lock)

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(self.address, timeout=30)
            ctx = _tls_context(server=False)
            if ctx is not None:
                sock = ctx.wrap_socket(sock)
            self._sock = sock
            self._reader = threading.Thread(
                target=self._drain, args=(sock,), daemon=True,
                name=f"rpc-pipeline-{self.address[1]}")
            self._reader.start()
        return self._sock

    def send(self, method: str, tag: Any = None, **kwargs) -> str:
        """Enqueue one request; returns its request id (dedupe key for
        any resubmit). `tag` is handed to on_error if the server replies
        with a failure or the connection dies with this request
        un-acked. Raises only on immediate transport failure — the
        caller treats that like any node-unreachable send."""
        sanitize_hooks.sched_point("rpc.pipeline.send")
        with self._send_lock:
            if self._closed.is_set():
                raise ConnectionError("pipelined client closed")
            sock = self._ensure()  # raylint: disable=R2 -- send lock serializes pipelined writes on one socket by design; replies drain on a separate reader thread, so holds are bounded by sendall
            self._seq += 1
            rid = f"{self._id_prefix}:{self._seq}"
            with self._pending_lock:
                self._pending[self._seq] = (rid, tag)
            try:
                send_msg(sock, wire.Request(id=rid, method=method,  # raylint: disable=R2 -- see above: frame ordering on the shared socket is the invariant the lock provides
                                            kwargs=kwargs,
                                            ack=self._acked))
            except (ConnectionError, OSError):
                with self._pending_lock:
                    self._pending.pop(self._seq, None)
                self._teardown()
                raise
            except BaseException:
                # Encode failure (unpicklable payload): nothing reached
                # the wire, so the connection is fine — but the pending
                # entry MUST go, or every later reply pops the wrong
                # request (ack/tag desync).
                with self._pending_lock:
                    self._pending.pop(self._seq, None)
                raise
            return rid

    def _drain(self, sock: socket.socket) -> None:
        while True:
            # Loop-edge yield point BEFORE the closed check: the edge
            # is exactly where the historical close-before-flush bug
            # raced (a close() setting _closed between a processed
            # reply and this re-check swept about-to-be-acked requests
            # into the orphan path) — the schedule harness parks the
            # reader here to replay that window deterministically.
            sanitize_hooks.sched_point("rpc.pipeline.reader_edge")
            if self._closed.is_set():
                break
            try:
                reply = recv_msg(sock)
            except wire.WireError:
                # Malformed or oversized reply frame: the reader can
                # no longer trust the stream — tear down exactly like
                # a connection loss so every pending request surfaces
                # through on_error, instead of the reader thread dying
                # on the untyped escape with the orphans parked
                # forever.
                break
            except (ConnectionError, OSError):
                break
            with self._pending_lock:
                if not self._pending:
                    continue
                seq, (rid, tag) = self._pending.popitem(last=False)
                self._acked = seq
                self._drained.notify_all()
            sanitize_hooks.sched_point("rpc.pipeline.reply_handled")
            if isinstance(reply, wire.Reply) and not reply.ok and \
                    self._on_error is not None:
                try:
                    self._on_error(tag, reply.error or "request failed",
                                   rid, False)
                except Exception:
                    pass
        # Connection gone: tear the socket down so the next send()
        # reconnects with a fresh reader instead of black-holing into a
        # half-closed fd, then surface everything still unacknowledged.
        # (Only if the live socket is still OURS — a send() may already
        # have reconnected and started a new reader.)
        with self._send_lock:
            if self._sock is sock:
                self._teardown()
        with self._pending_lock:
            orphans = list(self._pending.values())
            self._pending.clear()
            self._drained.notify_all()
        if self._on_error is not None:
            for rid, tag in orphans:
                try:
                    self._on_error(tag, "connection lost before ack",
                                   rid, True)
                except Exception:
                    pass

    @property
    def in_flight(self) -> int:
        with self._pending_lock:
            return len(self._pending)

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every sent request has been acknowledged."""
        deadline = time.monotonic() + timeout
        with self._pending_lock:
            while self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drained.wait(remaining)
        return True

    def _teardown(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._reader = None

    def close(self, flush_timeout: float = 0.0):
        """Tear the channel down. ``flush_timeout > 0`` first waits
        (via :meth:`flush`) for every sent request to be acknowledged —
        the clean-shutdown form; a closing channel must not silently
        drop requests the peer never confirmed. The default immediate
        form is for failure paths where the peer is already gone and
        waiting for acks could only time out.

        The flush runs BEFORE ``_closed`` is set: the reader thread
        exits its drain loop once ``_closed`` is visible, and an early
        exit would sweep still-pending (about-to-be-acked) requests
        into the orphan path — exactly the spurious failure-resubmit a
        clean shutdown exists to avoid."""
        if flush_timeout > 0:
            self.flush(flush_timeout)
        self._closed.set()
        # Schedule seam AFTER the closed flag: the race-replay fixture
        # scripts this against the reader's loop edge to prove the
        # flush-before-closed ordering holds (reverting it swept
        # about-to-be-acked requests into the orphan path).
        sanitize_hooks.sched_point("rpc.pipeline.closed_set")
        with self._send_lock:
            self._teardown()
