"""Framed TCP RPC: the cluster control/data plane transport.

Role-equivalent to the reference's gRPC layer (`src/ray/rpc/`): a threaded
server dispatching named methods, and a client with pooled connections.
The envelope and all standard-typed payloads ride the typed wire format
(`_private/wire.py` — the protobuf-contracts role: declared, versioned
`Request`/`Reply` messages, validated at decode); only user payloads
(functions, custom objects) are carried as explicitly-tagged opaque
(cloudpickle) sections. On TPU-VM fleets the control plane rides DCN and
this framing is sufficient; the tensor plane never touches it (XLA
collectives own ICI).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private import wire

# Cap on the server-side TLS handshake so one stalled/half-open peer can
# only pin its own connection thread, never the accept loop.
_TLS_HANDSHAKE_TIMEOUT_S = 10.0

_LEN = struct.Struct("!I")
# Reply retention is per client (keyed by the client's id prefix), not a
# global FIFO: a request with sequence N implicitly acks every reply with
# sequence < N from that client (the client holds a lock across each
# call+retry), so each client retains at most its in-flight reply. The
# only global bound needed is on the number of distinct clients.
_MAX_CLIENT_CACHES = 4096


def _tls_context(server: bool):
    """Mutual-TLS context when `use_tls` is configured (reference:
    RAY_USE_TLS + RAY_TLS_* in rpc/grpc_server); None = plaintext."""
    from ray_tpu._private.config import ray_config

    if not ray_config.use_tls:
        return None
    import ssl

    if not (ray_config.tls_server_cert and ray_config.tls_server_key
            and ray_config.tls_ca_cert):
        raise ValueError("use_tls requires tls_server_cert, "
                         "tls_server_key, and tls_ca_cert")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER if server
                         else ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(ray_config.tls_server_cert,
                        ray_config.tls_server_key)
    ctx.load_verify_locations(ray_config.tls_ca_cert)
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = False  # fleet nodes verify by CA, not hostname
    return ctx


def routable_host(peer_address: Tuple[str, int]) -> str:
    """The local interface IP a peer at ``peer_address`` would reach us
    on (UDP-connect trick — the kernel picks the outbound interface; no
    packet is sent). Nodes advertise this instead of loopback so object
    and control endpoints work across hosts; falls back to loopback."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((peer_address[0], peer_address[1] or 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = wire.encode(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    return wire.decode(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


class RpcServer:
    """Threaded request/response server: {method, kwargs} → {ok, result}.

    Methods listed in ``dedupe_methods`` get exactly-once semantics under
    client retry: completed replies are retained per client until that
    client's next request acks them (request seq N acks replies < N), and
    a retry racing a still-running execution waits for that execution
    instead of starting a second one. A waiter that finds the reply gone
    (client cache evicted) gets an error reply — never a re-execution.
    Idempotent methods skip the cache so large replies (e.g. object
    payloads) aren't retained.
    """

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0,
                 dedupe_methods: Optional[frozenset] = None):
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                if tls_ctx is not None:
                    # Handshake here, in the per-connection thread — never
                    # in get_request(), where a half-open peer would wedge
                    # the single accept loop for every node. A bounded
                    # timeout caps how long a stalled handshake can hold
                    # this thread. wrap_socket() detaches the raw socket,
                    # so socketserver's shutdown_request() no longer
                    # reaches the real fd — close the wrapped socket
                    # ourselves in finish().
                    try:
                        self.request.settimeout(_TLS_HANDSHAKE_TIMEOUT_S)
                        self.request = tls_ctx.wrap_socket(
                            self.request, server_side=True)
                        self.request.settimeout(None)
                    except (OSError, ValueError):  # SSLError is OSError
                        return
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, OSError):
                        return
                    if not isinstance(msg, wire.Request):
                        return  # typed-envelope violation: drop peer
                    rid = msg.id or None
                    if msg.method not in server_self.dedupe_methods:
                        rid = None
                    reply = server_self._await_reply(rid) if rid else None
                    if reply is None:
                        t0 = time.perf_counter()
                        try:
                            fn = server_self.handlers[msg.method]
                            result = fn(**(msg.kwargs or {}))
                            reply = wire.Reply(ok=True, result=result)
                        except BaseException as e:  # noqa: BLE001
                            import traceback

                            reply = wire.Reply(
                                ok=False,
                                error=f"{type(e).__name__}: {e}",
                                traceback=traceback.format_exc())
                        server_self._record_handler(
                            msg.method, time.perf_counter() - t0,
                            ok=reply.ok)
                        server_self._finish_reply(rid, reply)
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        return

            def finish(self):
                if tls_ctx is not None:
                    # self.request is the SSL-wrapped socket (or the raw
                    # one if the handshake failed); closing it sends
                    # close_notify and releases the detached fd that
                    # socketserver's shutdown_request can no longer see.
                    try:
                        self.request.close()
                    except OSError:
                        pass

        tls_ctx = _tls_context(server=True)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

            # NB: no get_request() override — the TLS handshake must not
            # run in the accept thread (see Handler.handle above).

        self.handlers = handlers
        self.dedupe_methods = dedupe_methods or frozenset()
        # client id prefix → {seq: reply}; OrderedDict for LRU over clients.
        self._replies: OrderedDict[str, Dict[int, Any]] = OrderedDict()
        self._inflight: Dict[str, threading.Event] = {}
        self._replies_lock = threading.Lock()
        self._handler_stats: Dict[str, Dict[str, float]] = {}
        self._stats_lock = threading.Lock()
        self._server = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"rpc-server-{self.address[1]}")
        self._thread.start()

    def add_handler(self, name: str, fn: Callable):
        self.handlers[name] = fn

    # -- per-handler stats (reference: instrumented_io_context +
    # event_stats — per-handler latency visibility on control loops) ----

    def _record_handler(self, method: str, seconds: float, ok: bool):
        with self._stats_lock:
            st = self._handler_stats.setdefault(
                method, {"calls": 0, "errors": 0, "total_s": 0.0,
                         "max_s": 0.0})
            st["calls"] += 1
            if not ok:
                st["errors"] += 1
            st["total_s"] += seconds
            st["max_s"] = max(st["max_s"], seconds)

    def handler_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-method call counts and latency aggregates."""
        with self._stats_lock:
            out = {}
            for method, st in self._handler_stats.items():
                mean = st["total_s"] / st["calls"] if st["calls"] else 0
                out[method] = {
                    "calls": st["calls"], "errors": st["errors"],
                    "mean_ms": round(mean * 1e3, 3),
                    "max_ms": round(st["max_s"] * 1e3, 3),
                    "total_s": round(st["total_s"], 3),
                }
            return out

    @staticmethod
    def _split_rid(rid: str) -> Tuple[str, int]:
        prefix, _, seq = rid.rpartition(":")
        return prefix, int(seq)

    def _await_reply(self, rid: str):
        """Cached reply for rid, waiting out an in-flight execution."""
        prefix, seq = self._split_rid(rid)
        with self._replies_lock:
            per_client = self._replies.get(prefix)
            if per_client is not None:
                cached = per_client.get(seq)
                if cached is not None:
                    return cached
                # Seeing seq means the client received every reply < seq
                # (it serializes call+retry under one lock) — drop them.
                for old in [s for s in per_client if s < seq]:
                    del per_client[old]
            event = self._inflight.get(rid)
            if event is None:
                # First sighting: claim the id; caller executes.
                self._inflight[rid] = threading.Event()
                return None
        event.wait()
        with self._replies_lock:
            reply = self._replies.get(prefix, {}).get(seq)
        if reply is None:
            # Cache evicted between finish and wakeup: fail the retry
            # rather than silently executing a second time.
            return wire.Reply(
                ok=False,
                error="RetryError: reply for retried request expired "
                      "before delivery")
        return reply

    def _finish_reply(self, rid: Optional[str], reply: Any):
        if rid is None:
            return
        prefix, seq = self._split_rid(rid)
        with self._replies_lock:
            per_client = self._replies.setdefault(prefix, {})
            per_client[seq] = reply
            self._replies.move_to_end(prefix)
            while len(self._replies) > _MAX_CLIENT_CACHES:
                self._replies.popitem(last=False)
            event = self._inflight.pop(rid, None)
        if event is not None:
            event.set()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """One logical connection per target address, thread-safe via a lock
    per connection (requests are small; head fan-in is the bottleneck long
    before this is)."""

    _pools: Dict[Tuple[str, int], "RpcClient"] = {}
    _pools_lock = threading.Lock()

    def __init__(self, address: Tuple[str, int]):
        self.address = tuple(address)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._id_prefix = uuid.uuid4().hex[:12]
        self._seq = 0

    @classmethod
    def to(cls, address) -> "RpcClient":
        key = tuple(address)
        with cls._pools_lock:
            client = cls._pools.get(key)
            if client is None:
                client = cls(key)
                cls._pools[key] = client
            return client

    @classmethod
    def dedicated(cls, address) -> "RpcClient":
        """A non-pooled client with its own connection. Required for
        long-poll calls (pubsub subscribe): the pooled client serializes
        calls on one socket, so a 10s poll would head-of-line block every
        other RPC this process sends to the same address."""
        return cls(tuple(address))

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(self.address, timeout=30)
            ctx = _tls_context(server=False)
            if ctx is not None:
                sock = ctx.wrap_socket(sock)
            self._sock = sock
        return self._sock

    def call(self, method: str, **kwargs) -> Any:
        with self._lock:
            self._seq += 1
            rid = f"{self._id_prefix}:{self._seq}"
            for attempt in (0, 1):
                try:
                    sock = self._ensure()
                    send_msg(sock, wire.Request(id=rid, method=method,
                                                kwargs=kwargs))
                    reply = recv_msg(sock)
                    break
                except (ConnectionError, OSError):
                    self.close_locked()
                    if attempt:
                        raise
        if not isinstance(reply, wire.Reply):
            raise RemoteCallError(
                f"{method} on {self.address}: malformed reply "
                f"{type(reply).__name__}")
        if not reply.ok:
            raise RemoteCallError(
                f"{method} failed on {self.address}: {reply.error}\n"
                + (reply.traceback or ""))
        return reply.result

    def close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self.close_locked()


class RemoteCallError(RuntimeError):
    pass
