"""Resource bookkeeping for scheduling.

Role-equivalent to the reference's ``ClusterResourceManager`` /
``LocalResourceManager`` fixed-point resource accounting
(``src/ray/raylet/scheduling/cluster_resource_data.h``). Quantities are kept
as integer milli-units (1 CPU == 1000) to avoid float drift, mirroring the
reference's FixedPoint. TPU chips are a first-class resource (``TPU``), and
nodes may carry ICI topology labels (e.g. ``ici_slice="v5e-64/0"``) used by
placement groups to demand contiguous slices.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

MILLI = 1000

# Canonical resource names.
CPU = "CPU"
TPU = "TPU"
GPU = "GPU"  # accepted for API compatibility; maps onto accelerators
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


def to_milli(resources: Dict[str, float]) -> Dict[str, int]:
    out = {}
    for name, qty in resources.items():
        if qty < 0:
            raise ValueError(f"resource {name} quantity must be >= 0, got {qty}")
        m = round(qty * MILLI)
        if m == 0 and qty > 0:
            raise ValueError(f"resource {name} quantity {qty} too small (<0.001)")
        out[name] = m
    return out


def spec_milli(spec) -> Dict[str, int]:
    """Template-cached milli-demand of a spec or queued header. Cached
    per spec: the conversion runs at least three times per task
    (pending add/remove + dispatch) plus the head's reservation and
    backlog accounting otherwise."""
    m = getattr(spec, "_milli_cache", None)
    if m is None:
        m = to_milli(spec.resources)
        try:
            spec._milli_cache = m
        except Exception:
            pass
    return m


def from_milli(resources: Dict[str, int]) -> Dict[str, float]:
    return {k: v / MILLI for k, v in resources.items()}


class ResourceSet:
    """Total/available resource quantities for one node, with blocking acquire."""

    def __init__(self, total: Dict[str, float]):
        self._total = to_milli(total)
        self._available = dict(self._total)
        self._cond = threading.Condition()

    @property
    def total(self) -> Dict[str, float]:
        return from_milli(self._total)

    @property
    def available(self) -> Dict[str, float]:
        with self._cond:
            return from_milli(self._available)

    def can_fit_total(self, request: Dict[str, int]) -> bool:
        """Feasibility: could this node ever satisfy the request?"""
        return all(self._total.get(k, 0) >= v for k, v in request.items())

    def try_acquire(self, request: Dict[str, int]) -> bool:
        with self._cond:
            if all(self._available.get(k, 0) >= v for k, v in request.items()):
                for k, v in request.items():
                    self._available[k] = self._available.get(k, 0) - v
                return True
            return False

    def release(self, request: Dict[str, int]) -> None:
        with self._cond:
            for k, v in request.items():
                self._available[k] = min(
                    self._available.get(k, 0) + v, self._total.get(k, v)
                )
            self._cond.notify_all()

    def add_capacity(self, extra: Dict[str, int]) -> None:
        """Grow the node (used by placement-group bundle reservation)."""
        with self._cond:
            for k, v in extra.items():
                self._total[k] = self._total.get(k, 0) + v
                self._available[k] = self._available.get(k, 0) + v
            self._cond.notify_all()

    def remove_capacity(self, extra: Dict[str, int]) -> None:
        with self._cond:
            for k, v in extra.items():
                self._total[k] = max(0, self._total.get(k, 0) - v)
                self._available[k] = max(0, self._available.get(k, 0) - v)

    def wait_for_change(self, timeout: Optional[float] = None) -> None:
        with self._cond:
            self._cond.wait(timeout)

    def utilization(self) -> float:
        """Fraction of (declared) resources in use; scheduling score input."""
        with self._cond:
            fracs = [
                1.0 - self._available.get(k, 0) / t
                for k, t in self._total.items()
                if t > 0
            ]
        return max(fracs) if fracs else 0.0


def normalize_request(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    default_cpus: float = 1.0,
) -> Dict[str, float]:
    """Build the canonical resource request for a task/actor.

    Mirrors the defaulting rules of ``@ray.remote`` option validation
    (reference ``python/ray/_private/ray_option_utils.py``): tasks default to
    1 CPU; explicit zeros are allowed (actors default to 0 CPU at the call
    site by passing default_cpus=0).
    """
    request: Dict[str, float] = {}
    for label, v in (("num_cpus", num_cpus), ("num_tpus", num_tpus),
                     ("num_gpus", num_gpus), ("memory", memory)):
        if v is not None and v < 0:
            raise ValueError(f"{label} must be >= 0, got {v}")
    for name, qty in (resources or {}).items():
        if qty < 0:
            raise ValueError(f"resources[{name!r}] must be >= 0, got {qty}")
    request[CPU] = default_cpus if num_cpus is None else float(num_cpus)
    if num_tpus:
        request[TPU] = float(num_tpus)
    if num_gpus:
        request[GPU] = float(num_gpus)
    if memory:
        request[MEMORY] = float(memory)
    for name, qty in (resources or {}).items():
        if name in (CPU, TPU, GPU):
            raise ValueError(
                f"Use num_cpus/num_tpus/num_gpus instead of resources[{name!r}]"
            )
        request[name] = float(qty)
    return {k: v for k, v in request.items() if v != 0 or k == CPU}
