"""Prefix/KV-cache decision core: block-granular prefix tree with
refcounts, LRU eviction, and per-tenant byte charges.

Role-equivalent to vLLM's prefix-caching block table (hash-chained
token chunks → KV blocks), reduced to the *decision* half: which blocks
exist, who may read them, which block is evicted under pressure, and
which tenant pays for the bytes. The PAYLOAD (the actual KV tensors)
lives outside — ``serve/llm.py`` keeps hot payloads host-side and
spills evicted-but-warm blocks to the shm object plane — so this core
stays pure: a lock, dicts, and counters. No RPC, no threads, no jax.

Chain keys: a prompt is split into fixed ``block_tokens`` chunks; each
chunk's key is a hash of (parent key, chunk tokens, seed), so a key
identifies the chunk AND its entire prefix — two prompts share a block
exactly when they share the whole head up to it. The ``seed`` carries
the model identity (multi-model replicas must never cross-hit).

Contracts (the rayspec ``kv_cache`` sequential spec — checked by
tests/core/test_rayspec.py and the raymc ``kv_cache_reuse`` scenario):

- a block with a nonzero refcount (a reader copied it into a slot, or
  an admit is still filling it) is NEVER evicted — a hit never yields
  freed bytes;
- refcounts never go negative: ``release`` without a matching
  ``lookup``/``pin``/``admit`` hold raises;
- per-tenant charge is conserved: a job's charge equals the bytes of
  its resident blocks, across every admit/evict interleaving;
- resident bytes never exceed ``capacity_bytes``.

Operation boundaries are tapped for rayspec (``spec.kv.*``) and gated
for raymc (``llm.kv.*``) — both registered in
``sanitize_hooks.SPEC_POINTS``/``SCHED_POINTS``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import perf_stats, sanitize_hooks


def chunk_hash(parent: str, tokens: Sequence[int], seed: str = "") -> str:
    """Key of one token chunk given its parent chunk's key. Stable
    across processes/replicas (the shm-tier object ids and the
    affinity-routing digests derive from it)."""
    h = hashlib.blake2b(digest_size=12)
    h.update(parent.encode())
    h.update(b"|")
    h.update(seed.encode())
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    return h.hexdigest()


def chain_keys(tokens: Sequence[int], block_tokens: int,
               seed: str = "") -> List[str]:
    """Hash-chain keys for every FULL ``block_tokens`` chunk of
    ``tokens`` (the partial tail chunk is never cached)."""
    if block_tokens <= 0:
        return []
    keys: List[str] = []
    parent = ""
    n_full = len(tokens) - len(tokens) % block_tokens
    for i in range(0, n_full, block_tokens):
        parent = chunk_hash(parent, tokens[i:i + block_tokens], seed)
        keys.append(parent)
    return keys


@dataclasses.dataclass(frozen=True)
class BlockHandle:
    """A pinned reference to a resident block: ``block_id`` names the
    payload generation (a re-admitted key gets a fresh id, so a stale
    payload read is detectable), ``index`` is the chunk position."""

    key: str
    block_id: int
    index: int


@dataclasses.dataclass(frozen=True)
class EvictedBlock:
    key: str
    block_id: int
    job: str
    nbytes: int
    index: int


class _Block:
    __slots__ = ("key", "block_id", "job", "nbytes", "refs", "index")

    def __init__(self, key, block_id, job, nbytes, index):
        self.key = key
        self.block_id = block_id
        self.job = job
        self.nbytes = nbytes
        self.refs = 1
        self.index = index


class PrefixCache:
    """The decision core. Thread-safe; every public op is one lock
    hold. See module docstring for the contract."""

    def __init__(self, capacity_bytes: int, block_tokens: int):
        self.capacity_bytes = int(capacity_bytes)
        self.block_tokens = int(block_tokens)
        self._lock = threading.Lock()
        self._blocks: Dict[str, _Block] = {}
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # LRU→MRU
        self._charge: Dict[str, int] = {}
        self._bytes = 0
        self._ids = itertools.count(1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._c_hits = perf_stats.counter("llm_kv_cache_hits")
        self._c_misses = perf_stats.counter("llm_kv_cache_misses")
        self._c_evict = perf_stats.counter("llm_kv_cache_evictions")
        self._c_bytes = perf_stats.counter("llm_kv_cache_bytes")

    # -- read path --------------------------------------------------------

    def lookup(self, chain: Sequence[str],
               job: str = "default") -> List[BlockHandle]:
        """Longest resident prefix of ``chain``, each block PINNED
        (refs+1) so no concurrent admit/evict frees it while the caller
        copies the payload. Callers must :meth:`release` every handle."""
        chain = tuple(chain)
        sanitize_hooks.sched_point("llm.kv.lookup")
        sanitize_hooks.spec_op("spec.kv.lookup", "call", self, (chain,))
        out: List[BlockHandle] = []
        with self._lock:
            for i, key in enumerate(chain):
                block = self._blocks.get(key)
                if block is None:
                    break
                block.refs += 1
                self._lru.move_to_end(key)
                out.append(BlockHandle(key, block.block_id, i))
            self.hits += len(out)
            self.misses += len(chain) - len(out)
        self._c_hits.inc(len(out))
        self._c_misses.inc(len(chain) - len(out))
        sanitize_hooks.spec_op("spec.kv.lookup", "ret", self, len(out))
        return out

    def pin(self, handles: Sequence[BlockHandle]) -> None:
        """Extra refs on already-held handles (e.g. one copy-in per
        destination slot). Pinning a block the caller does not hold is
        a bug and raises."""
        keys = tuple(h.key for h in handles)
        sanitize_hooks.spec_op("spec.kv.pin", "call", self, (keys,))
        with self._lock:
            for h in handles:
                block = self._blocks.get(h.key)
                if block is None or block.block_id != h.block_id \
                        or block.refs < 1:
                    raise ValueError(
                        f"pin of unheld block {h.key!r}")
            for h in handles:
                self._blocks[h.key].refs += 1
        sanitize_hooks.spec_op("spec.kv.pin", "ret", self, None)

    def release(self, handles: Sequence[BlockHandle]) -> None:
        """Drop one ref per handle. A release past zero means a caller
        double-released — a freed-bytes-in-flight bug — and raises."""
        keys = tuple(h.key for h in handles)
        sanitize_hooks.sched_point("llm.kv.release")
        sanitize_hooks.spec_op("spec.kv.release", "call", self, (keys,))
        with self._lock:
            for h in handles:
                block = self._blocks.get(h.key)
                if block is None or block.refs < 1:
                    raise ValueError(
                        f"release without a matching hold on {h.key!r}")
            for h in handles:
                self._blocks[h.key].refs -= 1
        sanitize_hooks.spec_op("spec.kv.release", "ret", self, None)

    # -- write path -------------------------------------------------------

    def admit(self, chain: Sequence[str], job: str, nbytes: int) \
            -> Tuple[List[BlockHandle], List[EvictedBlock]]:
        """Insert the missing blocks of ``chain`` (``nbytes`` each,
        charged to ``job``), evicting LRU unpinned blocks for space.
        Created blocks come back PINNED (refs=1) so the caller can
        store the payload before any evict can touch them — the caller
        must :meth:`release` them afterwards. Admission stops at the
        first block that cannot fit (everything evictable is pinned):
        a child without its parent resident can never be looked up, so
        a partial-prefix admit is the correct degradation."""
        chain = tuple(chain)
        nbytes = int(nbytes)
        sanitize_hooks.sched_point("llm.kv.admit")
        sanitize_hooks.spec_op("spec.kv.admit", "call", self,
                               (chain, job, nbytes))
        created: List[BlockHandle] = []
        evicted: List[EvictedBlock] = []
        with self._lock:
            for i, key in enumerate(chain):
                block = self._blocks.get(key)
                if block is not None:
                    self._lru.move_to_end(key)
                    continue
                if nbytes > self.capacity_bytes:
                    break
                while self._bytes + nbytes > self.capacity_bytes:
                    victim = self._evict_one_locked()
                    if victim is None:
                        break
                    evicted.append(victim)
                if self._bytes + nbytes > self.capacity_bytes:
                    break  # everything evictable is pinned
                block = _Block(key, next(self._ids), job, nbytes, i)
                self._blocks[key] = block
                self._lru[key] = None
                self._bytes += nbytes
                self._charge[job] = self._charge.get(job, 0) + nbytes
                created.append(BlockHandle(key, block.block_id, i))
            self.evictions += len(evicted)
        delta = nbytes * len(created) - sum(e.nbytes for e in evicted)
        self._c_bytes.inc(delta)
        self._c_evict.inc(len(evicted))
        sanitize_hooks.spec_op(
            "spec.kv.admit", "ret", self,
            (tuple(h.key for h in created),
             tuple(e.key for e in evicted)))
        return created, evicted

    def evict(self, nbytes: int) -> List[EvictedBlock]:
        """Free at least ``nbytes`` of UNPINNED LRU blocks (or as much
        as is evictable) — the arena-pressure entry point."""
        sanitize_hooks.sched_point("llm.kv.evict")
        sanitize_hooks.spec_op("spec.kv.evict", "call", self,
                               (int(nbytes),))
        out: List[EvictedBlock] = []
        with self._lock:
            freed = 0
            while freed < nbytes:
                victim = self._evict_one_locked()
                if victim is None:
                    break
                freed += victim.nbytes
                out.append(victim)
            self.evictions += len(out)
        self._c_bytes.inc(-sum(e.nbytes for e in out))
        self._c_evict.inc(len(out))
        sanitize_hooks.spec_op("spec.kv.evict", "ret", self,
                               (tuple(e.key for e in out),))
        return out

    def _evict_one_locked(self) -> Optional[EvictedBlock]:
        """LRU victim among refs==0 blocks; None when every block is
        pinned. A pinned block is NEVER chosen — the core contract."""
        for key in self._lru:
            block = self._blocks[key]
            if block.refs == 0:
                del self._blocks[key]
                del self._lru[key]
                self._bytes -= block.nbytes
                left = self._charge.get(block.job, 0) - block.nbytes
                if left > 0:
                    self._charge[block.job] = left
                else:
                    self._charge.pop(block.job, None)
                return EvictedBlock(key, block.block_id, block.job,
                                    block.nbytes, block.index)
        return None

    # -- observation ------------------------------------------------------

    def hot_digests(self, top_n: int = 32) -> List[str]:
        """MRU-first resident block keys (bounded) — the affinity
        digest a replica exports through the membership long-poll."""
        with self._lock:
            out = []
            for key in reversed(self._lru):
                out.append(key)
                if len(out) >= top_n:
                    break
            return out

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._blocks

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def charges(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._charge)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
