"""Flight recorder: bounded rings of recent activity, frozen on
degradation into one correlated post-mortem snapshot.

A burn-rate alert tells the operator a route degraded; by the time a
human looks, the queue drained and the evidence is gone. Every process
therefore keeps cheap bounded ring buffers of what just happened:

- **span ring** — recent stage spans (fed by
  ``critical_path.record_stage``: one deque append on the hot path),
- **sample ring** — periodic health samples (queue depths, SLO burn,
  memory pressure, loop lag; fed by ``collect_health_metrics`` at
  scrape/ship cadence).

When ``evaluate_health()`` flips this process ok→degraded (or an
operator hits ``/api/debug/dump``), the head freezes the moment: its
own rings, every live node's rings (a ``flight_snapshot`` RPC — nodes
answer from their deques, no recomputation), the health verdict and
reasons that triggered it, and the slowest in-flight request
waterfalls from the critical-path engine. The correlated snapshot is
written as one ``FLIGHT_<ts>.json`` under ``flight_recorder_dir``.

Auto-dump gates on ``flight_recorder_dir`` being set (default "" — a
test suite flipping verdicts must not litter the filesystem) and
debounces by ``flight_min_interval_s`` so a flapping verdict costs one
dump per window, not one per healthz poll.

Layering: imports config/worker plumbing only; ``critical_path`` is
imported lazily at snapshot time (it imports this module at top level
for the hot-path ring feed).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from ray_tpu._private.config import ray_config

ENABLED = True

# Physical ring capacity. ``flight_ring_size`` (the shipped-snapshot
# bound) is read at freeze time so config changes apply live; the
# backing deques are sized once at the table's ceiling.
_RING_CAP = 2048

_spans: "deque[dict]" = deque(maxlen=_RING_CAP)
_samples: "deque[dict]" = deque(maxlen=_RING_CAP)

_lock = threading.Lock()
# ok→degraded edge detection + debounce for the auto-dump.
_last_status: Optional[str] = None
_last_dump_ts: float = 0.0
_dump_count: int = 0


def set_enabled(on: bool) -> None:
    """A/B kill switch (rides the same ``--ab-observability`` leg as
    the critical-path engine)."""
    global ENABLED
    ENABLED = bool(on)


def note_span(rec) -> None:
    """Hot path: one GIL-atomic bounded append. ``rec`` is the
    critical-path record tuple ``(t, trace_id, stage, dur_s, route)``
    (dicts from older callers pass through); the dict shape is built
    at freeze time, not per span."""
    if ENABLED:
        _spans.append(rec)


def _span_dict(rec) -> dict:
    if isinstance(rec, tuple):
        t, trace_id, stage, dur_s, route = rec
        return {"t": t, "trace_id": trace_id, "stage": stage,
                "dur_s": dur_s, "route": route}
    return rec


def note_sample(kind: str, data: Dict[str, Any]) -> None:
    """Scrape-cadence path: queue depths, burn rates, pressure."""
    if ENABLED:
        _samples.append({"kind": kind, "t": time.time(), **data})


def local_snapshot() -> dict:
    """Freeze this process's rings (plus its in-flight slow-request
    waterfalls) into plain data — the ``flight_snapshot`` RPC answer
    and the head's own contribution to a dump."""
    from ray_tpu._private import critical_path

    critical_path.flush()  # ring is fed at fold time, not append time
    n = max(1, int(ray_config.flight_ring_size))
    spans = [_span_dict(r) for r in list(_spans)[-n:]]
    samples = list(_samples)[-n:]
    try:
        slow = critical_path.slow_requests(10, include_inflight=True)
    except Exception:
        slow = []
    return {"pid": os.getpid(), "ts": time.time(),
            "spans": spans, "samples": samples,
            "slow_requests": slow}


def _collect_node_rings(worker) -> Dict[str, dict]:
    """Per-node rings: the head's own, plus a ``flight_snapshot`` RPC
    to every live registered node. A node that fails to answer gets an
    error marker instead of poisoning the dump — a post-mortem of a
    degraded cluster must tolerate degraded nodes."""
    rings: Dict[str, dict] = {}
    local_id = getattr(worker, "node_id", None) or "head"
    rings[str(local_id)] = local_snapshot()
    head = getattr(worker, "cluster_head", None)
    if head is None:
        return rings
    from ray_tpu._private.rpc import RpcClient

    for node_id, record in sorted(getattr(head, "nodes", {}).items()):
        if not getattr(record, "alive", True) or node_id in rings:
            continue
        try:
            rings[node_id] = RpcClient.to(record.address).call(
                "flight_snapshot")
        except Exception as e:
            rings[node_id] = {"error": f"{type(e).__name__}: {e}"}
    return rings


def dump(trigger: str, worker=None, verdict: Optional[dict] = None,
         out_dir: Optional[str] = None,
         write: Optional[bool] = None) -> dict:
    """Produce one correlated flight snapshot. Returns the payload
    (plus ``"path"`` when written). ``write`` defaults to "dir is
    configured"; ``/api/debug/dump`` passes the payload inline either
    way."""
    from ray_tpu._private.worker import global_worker_or_none

    w = worker or global_worker_or_none()
    payload: Dict[str, Any] = {
        "trigger": trigger,
        "ts": time.time(),
        "verdict": (verdict or {}).get("status", "unknown"),
        "reasons": list((verdict or {}).get("reasons") or ()),
        "nodes": _collect_node_rings(w) if w is not None
        else {"head": local_snapshot()},
    }
    # The head-wide slowest waterfalls (its critical-path engine sees
    # every proxied request plus shipped node stages) sit at top level
    # so the first page of the dump names the dominant stages.
    from ray_tpu._private import critical_path

    try:
        payload["slow_requests"] = critical_path.slow_requests(
            10, include_inflight=True)
    except Exception:
        payload["slow_requests"] = []
    directory = out_dir if out_dir is not None \
        else ray_config.flight_recorder_dir
    should_write = bool(directory) if write is None else write
    if should_write and directory:
        global _dump_count
        with _lock:
            _dump_count += 1
            seq = _dump_count
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"FLIGHT_{int(payload['ts'])}_{seq}.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)
        payload["path"] = path
    return payload


def observe_verdict(verdict: dict, worker=None) -> Optional[dict]:
    """Edge-triggered auto-dump hook: ``evaluate_health`` calls this
    with every computed verdict. On the ok→degraded transition — with
    a dump directory configured and the debounce window elapsed — the
    moment is frozen to disk. Returns the dump payload when one was
    produced (tests key off it), else None."""
    global _last_status, _last_dump_ts
    if not ENABLED:
        return None
    status = verdict.get("status")
    with _lock:
        prev = _last_status
        _last_status = status
        if status != "degraded" or prev == "degraded":
            return None
        if not ray_config.flight_recorder_dir:
            return None
        now = time.time()
        if now - _last_dump_ts < ray_config.flight_min_interval_s:
            return None
        _last_dump_ts = now
    try:
        return dump("degraded", worker=worker, verdict=verdict)
    except Exception:
        return None  # the post-mortem must never break healthz


# -- test isolation -----------------------------------------------------------


def snapshot_state() -> dict:
    """Plain-data snapshot (IN PLACE restore contract — hot paths
    alias the module deques) for the conftest baseline fixture."""
    with _lock:
        return {"enabled": ENABLED, "spans": list(_spans),
                "samples": list(_samples), "last_status": _last_status,
                "last_dump_ts": _last_dump_ts,
                "dump_count": _dump_count}


def restore_state(snapshot: dict) -> None:
    global ENABLED, _last_status, _last_dump_ts, _dump_count
    with _lock:
        ENABLED = snapshot.get("enabled", True)
        _spans.clear()
        _spans.extend(snapshot.get("spans", ()))
        _samples.clear()
        _samples.extend(snapshot.get("samples", ()))
        _last_status = snapshot.get("last_status")
        _last_dump_ts = snapshot.get("last_dump_ts", 0.0)
        _dump_count = snapshot.get("dump_count", 0)


def reset() -> None:
    restore_state({"enabled": True})
