"""Memory monitor + worker killing under memory pressure.

Role-equivalent to the reference's `src/ray/common/memory_monitor.h:52`
(cgroup/proc-based usage sampling) driving the raylet's worker-killing
policies (`worker_killing_policy_retriable_fifo.h`,
`worker_killing_policy_group_by_owner.h`): when node memory usage crosses
the threshold, kill a worker *process* — preferring the newest retriable
task, so the victim can re-run once pressure clears — instead of letting
the kernel OOM-killer take down the whole node.

Only process-isolated work (``isolate_process`` tasks and actors) is
killable; in-thread tasks share the node's address space, which is
exactly why the worker pool exists.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ray_tpu._private.config import ray_config

logger = logging.getLogger(__name__)

# Last sampled usage fraction (value, monotonic ts) — the health plane
# reads this instead of re-walking /proc on every verdict. Written by
# whichever monitor loop sampled last; a plain tuple swap is atomic
# under the GIL.
_last_sample: "tuple[float, float] | None" = None


def current_pressure(max_age_s: float = 2.0) -> float:
    """Node memory usage fraction for the health/metrics plane: the
    monitor loop's latest sample when fresh, else sampled inline (the
    no-monitor case — drivers, tests — still gets a live value; the
    read is two small file reads)."""
    import time

    global _last_sample
    sample = _last_sample
    now = time.monotonic()
    if sample is not None and now - sample[1] <= max_age_s:
        return sample[0]
    value = system_memory_usage_fraction()
    _last_sample = (value, now)
    return value


def system_memory_usage_fraction() -> float:
    """Used fraction of node memory: cgroup v2 limit when present (the
    container case, as the reference prefers), else /proc/meminfo."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            limit_raw = f.read().strip()
        if limit_raw != "max":
            with open("/sys/fs/cgroup/memory.current") as f:
                current = int(f.read().strip())
            return current / max(int(limit_raw), 1)
    except OSError:
        pass
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                name, _, rest = line.partition(":")
                info[name] = int(rest.strip().split()[0])
        total = info.get("MemTotal", 0)
        available = info.get("MemAvailable", 0)
        if total:
            return 1.0 - available / total
    except OSError:  # pragma: no cover - non-Linux
        pass
    return 0.0


class MemoryMonitor:
    """Samples usage on a timer; above threshold, asks the backend to
    kill one killable worker per breach (repeats while pressure holds)."""

    def __init__(self, backend,
                 usage_fn: Optional[Callable[[], float]] = None):
        self.backend = backend
        self.usage_fn = usage_fn or system_memory_usage_fraction
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_killed = 0

    def start(self) -> None:
        if self._thread is not None or \
                ray_config.memory_monitor_refresh_ms <= 0:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        import time

        global _last_sample
        while not self._stop.wait(
                ray_config.memory_monitor_refresh_ms / 1000.0):
            try:
                usage = self.usage_fn()
            except Exception:  # pragma: no cover - sampling must not kill
                continue
            _last_sample = (usage, time.monotonic())
            if usage <= ray_config.memory_usage_threshold:
                continue
            if self.kill_one(usage):
                self.num_killed += 1

    def kill_one(self, usage: float) -> bool:
        """Retriable-FIFO policy (reference:
        `worker_killing_policy_retriable_fifo.h`): newest retriable task
        first — it loses the least work and can re-run; then the newest
        non-retriable. Returns True if something was killed."""
        pool = self.backend._worker_pool
        if pool is None:
            return False
        with pool._lock:
            active = list(pool.active.values())
        if not active:
            return False

        def sort_key(item):
            proc, spec, t0 = item
            retriable = bool(spec is not None and
                             getattr(spec, "max_retries", 0) != 0 and
                             getattr(spec, "retry_exceptions", False))
            return (not retriable, -t0)

        proc, spec, t0 = sorted(active, key=sort_key)[0]
        # Re-validate under the pool lock right before the SIGKILL: the
        # task may have finished (worker back in the idle pool, possibly
        # already running someone else's work) since the snapshot.
        with pool._lock:
            current = pool.active.get(proc.pid)
            if current is None or current[0] is not proc or \
                    current[2] != t0:
                return False
            logger.warning(
                "memory usage %.1f%% above threshold %.1f%%: killing "
                "worker %s running %s", usage * 100,
                ray_config.memory_usage_threshold * 100, proc.pid,
                spec.describe() if spec is not None else "<unknown>")
            proc.kill()
        self._record_kill_event(proc.pid, spec, usage)
        return True

    def _record_kill_event(self, pid: int, spec, usage: float) -> None:
        """The kill decision as a task event (victim task id, usage
        fraction, job tag): OOM kills show up in ``timeline()`` and the
        cluster-wide state views — shipped to the head like any task
        event — instead of only in this node's log. A synthetic task id
        keeps the incident distinct from the victim task's own record,
        which a retry will overwrite."""
        import time

        from ray_tpu._private import perf_stats
        from ray_tpu._private.task_events import TaskEvent

        try:
            victim = spec.task_id.hex() if spec is not None else ""
            now = time.time()
            self.backend.worker.task_events.record_event(TaskEvent(
                task_id=f"memkill:{victim or pid}:{self.num_killed}",
                name="memory_monitor.kill_worker",
                kind="NORMAL_TASK", state="MEMORY_KILLED",
                start_s=now, end_s=now,
                node_id=getattr(self.backend, "node_id", None).hex()
                if getattr(self.backend, "node_id", None) else "",
                worker=f"pid={pid}",
                error=f"worker killed at memory usage {usage:.3f} "
                      f"(threshold "
                      f"{ray_config.memory_usage_threshold:.3f}); "
                      f"victim task {victim or '<unknown>'}",
                job_id=(spec.job_id or "") if spec is not None else ""))
            perf_stats.counter("memory_monitor_kills").inc()
        except Exception:  # pragma: no cover — accounting must not
            pass           # interfere with the kill itself
