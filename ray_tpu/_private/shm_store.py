"""Python client for the native shared-memory object store.

Wraps `src/object_store` (the plasma-equivalent, see store.h) over ctypes
— no pybind11 in the image. Zero-copy reads: the client mmaps the same
segment and returns numpy views directly over object payloads (reference
parity: plasma's zero-copy numpy buffers, `plasma/client.h`).

The library builds on demand with g++ (`ensure_built`), cached under
`build/`.
"""

from __future__ import annotations

import contextlib
import ctypes
import mmap
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "object_store")
_BUILD = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD, "libray_tpu_store.so")

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class StoreStats(ctypes.Structure):
    _fields_ = [
        ("capacity", ctypes.c_uint64),
        ("allocated", ctypes.c_uint64),
        ("num_objects", ctypes.c_uint64),
        ("num_sealed", ctypes.c_uint64),
        ("evictions", ctypes.c_uint64),
        ("create_failures", ctypes.c_uint64),
    ]


class TransferStats(ctypes.Structure):
    _fields_ = [
        ("bytes_sent", ctypes.c_uint64),
        ("bytes_received", ctypes.c_uint64),
        ("objects_served", ctypes.c_uint64),
        ("objects_pulled", ctypes.c_uint64),
        ("errors", ctypes.c_uint64),
        ("objects_pushed_in", ctypes.c_uint64),
        ("bytes_pushed_in", ctypes.c_uint64),
    ]


def ensure_built() -> str:
    with _build_lock:
        srcs = [os.path.join(_SRC, f) for f in
                ("store.cc", "transfer.cc", "store.h", "transfer.h")]
        if os.path.exists(_LIB) and all(
                os.path.getmtime(_LIB) >= os.path.getmtime(s)
                for s in srcs):
            return _LIB
        os.makedirs(_BUILD, exist_ok=True)
        subprocess.run(  # raylint: disable=R2 -- _build_lock exists solely to make the one-time g++ compile once-only; every waiter needs the built artifact before it can proceed, so serializing them on the build IS the point
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o", _LIB,
             os.path.join(_SRC, "store.cc"),
             os.path.join(_SRC, "transfer.cc"), "-lpthread", "-lrt"],
            check=True, cwd=_SRC, capture_output=True)
        return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(ensure_built())
    lib.shm_store_create.restype = ctypes.c_void_p
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                     ctypes.c_uint32]
    lib.shm_store_attach.restype = ctypes.c_void_p
    lib.shm_store_attach.argtypes = [ctypes.c_char_p]
    lib.shm_store_close.argtypes = [ctypes.c_void_p]
    lib.shm_store_destroy.argtypes = [ctypes.c_char_p]
    lib.shm_obj_create.restype = ctypes.c_uint64
    lib.shm_obj_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    lib.shm_obj_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_obj_get.restype = ctypes.c_uint64
    lib.shm_obj_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_uint64)]
    lib.shm_obj_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_obj_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_obj_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_obj_refcount.restype = ctypes.c_int32
    lib.shm_obj_refcount.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_stats.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(StoreStats)]
    lib.shm_store_mmap_size.restype = ctypes.c_uint64
    lib.shm_store_mmap_size.argtypes = [ctypes.c_void_p]
    lib.shm_transfer_start.restype = ctypes.c_void_p
    lib.shm_transfer_start.argtypes = [ctypes.c_void_p, ctypes.c_uint16]
    lib.shm_transfer_port.restype = ctypes.c_uint16
    lib.shm_transfer_port.argtypes = [ctypes.c_void_p]
    lib.shm_transfer_stop.argtypes = [ctypes.c_void_p]
    lib.shm_transfer_pull.restype = ctypes.c_int
    lib.shm_transfer_pull.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_uint16]
    lib.shm_transfer_pull_opts.restype = ctypes.c_int
    lib.shm_transfer_pull_opts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint16, ctypes.c_int]
    lib.shm_transfer_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(TransferStats)]
    lib.shm_transfer_pull_striped.restype = ctypes.c_int
    lib.shm_transfer_pull_striped.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint16, ctypes.c_int, ctypes.c_int]
    lib.shm_transfer_push.restype = ctypes.c_int
    lib.shm_transfer_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_uint16]
    _lib = lib
    return lib


class ShmObjectStore:
    """One node's shared object store (create on the 'head', attach from
    workers)."""

    def __init__(self, name: str = "/ray_tpu_store",
                 capacity: int = 256 * 2**20, max_objects: int = 4096,
                 create: bool = True):
        self._lib = _load()
        self.name = name
        if create:
            self._handle = self._lib.shm_store_create(
                name.encode(), capacity, max_objects)
        else:
            self._handle = self._lib.shm_store_attach(name.encode())
        if not self._handle:
            raise OSError(f"failed to open shm store {name!r}")
        # Close/op gate: every ctypes entry point runs under _op(),
        # which refuses once closing starts; close() waits for in-
        # flight calls to drain before freeing the C handle and the
        # mapping. Without it, `contains()`/`put_bytes` racing
        # `close()` on another thread dereferences a freed handle —
        # a real observed SEGFAULT at publish-vs-teardown.
        self._op_cv = threading.Condition()
        self._op_inflight = 0
        self._closing = False
        # Map the segment into this process for zero-copy access.
        size = self._lib.shm_store_mmap_size(self._handle)
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            self._map = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._map)
        # Pre-fault the arena in the background — in EVERY process, not
        # just the creator: tmpfs pages materialize on first touch at
        # ~0.1 GB/s of fault overhead, and page-table entries are
        # per-process, so an attaching node writing 64 MB through cold
        # PTEs paid ~4x the warm copy cost (measured 81 ms vs 19 ms).
        # MADV_POPULATE_WRITE instantiates pages + PTEs kernel-side
        # without touching content (no race with concurrent writers).
        self._prefault_thread = threading.Thread(
            target=self._prefault, daemon=True, name="shm-prefault")
        self._prefault_thread.start()

    def wait_prefault(self, timeout: Optional[float] = None) -> None:
        t = getattr(self, "_prefault_thread", None)
        if t is not None:
            t.join(timeout)

    def _prefault(self):
        import ctypes

        try:
            libc = ctypes.CDLL("libc.so.6", use_errno=True)
            buf = (ctypes.c_char * len(self._map)).from_buffer(self._map)
            addr = ctypes.addressof(buf)
            madv_populate_write = 23  # linux uapi
            chunk = 16 * 2**20
            size = len(self._map)
            # Front-to-back: the allocator is first-fit, so early objects
            # land in already-populated regions.
            for off in range(0, size, chunk):
                n = min(chunk, size - off)
                libc.madvise(ctypes.c_void_p(addr + off),
                             ctypes.c_size_t(n), madv_populate_write)
        except Exception:
            pass  # populate is an optimization; faults still work

    @contextlib.contextmanager
    def _op(self):
        """Gate one native call against close(). Yields the live C
        handle, or None when the store is closing/closed (callers
        return a benign miss). The handle and mapping stay valid for
        the whole `with` body — close() blocks on the drain."""
        with self._op_cv:
            if self._closing or not self._handle:
                yield None
                return
            self._op_inflight += 1
        try:
            yield self._handle
        finally:
            with self._op_cv:
                self._op_inflight -= 1
                if self._op_inflight == 0:
                    self._op_cv.notify_all()

    # -- raw bytes -------------------------------------------------------

    def put_bytes(self, object_id: bytes, payload: bytes) -> bool:
        assert len(object_id) == 20
        with self._op() as h:
            if h is None:
                return False
            off = self._lib.shm_obj_create(h, object_id, len(payload))
            if off == 2**64 - 1:
                return False
            self._view[off:off + len(payload)] = payload
            return bool(self._lib.shm_obj_seal(h, object_id))

    def get_bytes(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view; call release(object_id) when done."""
        size = ctypes.c_uint64()
        with self._op() as h:
            if h is None:
                return None
            off = self._lib.shm_obj_get(h, object_id,
                                        ctypes.byref(size))
            if off == 2**64 - 1:
                return None
            return self._view[off:off + size.value]

    # -- numpy -----------------------------------------------------------

    def put_numpy(self, object_id: bytes, arr: np.ndarray) -> bool:
        arr = np.ascontiguousarray(arr)
        header = _encode_header(arr)
        total = len(header) + arr.nbytes
        with self._op() as h:
            if h is None:
                return False
            off = self._lib.shm_obj_create(h, object_id, total)
            if off == 2**64 - 1:
                return False
            self._view[off:off + len(header)] = header
            dst = np.frombuffer(self._view, np.uint8, arr.nbytes,
                                off + len(header))
            dst[:] = arr.view(np.uint8).reshape(-1)
            return bool(self._lib.shm_obj_seal(h, object_id))

    def get_numpy(self, object_id: bytes) -> Optional[np.ndarray]:
        """Zero-copy read-only array backed by shared memory."""
        buf = self.get_bytes(object_id)
        if buf is None:
            return None
        dtype, shape, hlen = _decode_header(buf)
        arr = np.frombuffer(buf, dtype=dtype, offset=hlen).reshape(shape)
        arr.flags.writeable = False
        return arr

    # -- lifecycle -------------------------------------------------------

    def contains(self, object_id: bytes) -> bool:
        with self._op() as h:
            if h is None:
                return False
            return bool(self._lib.shm_obj_contains(h, object_id))

    def object_size(self, object_id: bytes) -> Optional[int]:
        """Payload size of a sealed object, or None if absent."""
        size = ctypes.c_uint64()
        with self._op() as h:
            if h is None:
                return None
            off = self._lib.shm_obj_get(h, object_id,
                                        ctypes.byref(size))
            if off == 2**64 - 1:
                return None
            self._lib.shm_obj_release(h, object_id)  # drop Get's pin
            return size.value

    def release(self, object_id: bytes) -> bool:
        with self._op() as h:
            if h is None:
                return False
            return bool(self._lib.shm_obj_release(h, object_id))

    def delete(self, object_id: bytes) -> bool:
        with self._op() as h:
            if h is None:
                return False
            return bool(self._lib.shm_obj_delete(h, object_id))

    def refcount(self, object_id: bytes) -> int:
        """Pin count of a sealed object across ALL attached processes,
        or -1 when absent/unsealed (spill victim selection)."""
        with self._op() as h:
            if h is None:
                return -1
            return int(self._lib.shm_obj_refcount(h, object_id))

    def stats(self) -> dict:
        st = StoreStats()
        with self._op() as h:
            if h is None:
                return {f[0]: 0 for f in StoreStats._fields_}
            self._lib.shm_store_stats(h, ctypes.byref(st))
        return {f[0]: getattr(st, f[0]) for f in StoreStats._fields_}

    # -- transfer plane (node-to-node chunked pull; transfer.h) ---------

    def start_transfer_server(self, port: int = 0) -> int:
        """Serve this store's objects to remote pullers; returns port."""
        handle = self._lib.shm_transfer_start(self._handle, port)
        if not handle:
            raise OSError("failed to start transfer server")
        self._transfer = handle
        return self._lib.shm_transfer_port(handle)

    def stop_transfer_server(self):
        handle = getattr(self, "_transfer", None)
        if handle:
            self._lib.shm_transfer_stop(handle)
            self._transfer = None

    def transfer_stats(self) -> dict:
        handle = getattr(self, "_transfer", None)
        if not handle:
            return {}
        st = TransferStats()
        self._lib.shm_transfer_stats(handle, ctypes.byref(st))
        return {f[0]: getattr(st, f[0]) for f in TransferStats._fields_}

    def pull_from(self, object_id: bytes, host: str, port: int,
                  allow_local: bool = True) -> int:
        """Chunked C++ pull of a remote object into this store.
        0 = pulled, -5 = already present, <0 = failure (transfer.h).
        ``allow_local=False`` forces the TCP stream even when the peer's
        segment is mappable on this machine (remote-host simulation)."""
        with self._op() as h:
            if h is None:
                return -1
            return self._lib.shm_transfer_pull_opts(
                h, object_id, host.encode(), port,
                1 if allow_local else 0)

    def pull_from_striped(self, object_id: bytes, host: str, port: int,
                          streams: int = 4,
                          allow_local: bool = True) -> int:
        """Parallel range-striped pull (reference: object_manager
        chunked parallel pulls): `streams` connections each move a
        disjoint byte range. Wins on multi-core hosts / fast NICs;
        degrades to ~single-stream on one core."""
        with self._op() as h:
            if h is None:
                return -1
            return self._lib.shm_transfer_pull_striped(
                h, object_id, host.encode(), port, streams,
                1 if allow_local else 0)

    def push_to(self, object_id: bytes, host: str, port: int) -> int:
        """Proactively stream a LOCAL object into a remote store
        (reference push_manager.h). 0 = pushed, -5 = remote already has
        it, -2 = missing locally, <0 = failure."""
        with self._op() as h:
            if h is None:
                return -1
            return self._lib.shm_transfer_push(
                h, object_id, host.encode(), port)

    def close(self):
        self.stop_transfer_server()
        # Drain the op gate BEFORE freeing anything: a publisher mid-
        # `put_bytes`/`contains` on another thread still holds the C
        # handle and writes through the mapping. Flag first (new ops
        # turn into misses), then wait for in-flight ones. If a native
        # call wedges past the deadline (a blocking transfer pull),
        # LEAK the handle rather than free it under a live caller —
        # an unreclaimed segment beats a segfault.
        with self._op_cv:
            self._closing = True
            deadline = 10.0
            while self._op_inflight:
                before = self._op_inflight
                self._op_cv.wait(timeout=deadline)
                if self._op_inflight >= before:
                    break  # wedged: give up, leak below
            drained = self._op_inflight == 0
            handle, self._handle = self._handle, None
        if handle and drained:
            self._lib.shm_store_close(handle)
        if not drained:
            return
        # Drop this process's own mapping too: the mmap holds a dup'd
        # fd on the segment, so an unlinked store otherwise pins its
        # tmpfs pages via a "(deleted)" descriptor for the process
        # lifetime. Best-effort — zero-copy readers still holding
        # exported buffers keep the mapping valid (BufferError), which
        # is exactly the no-segfault guarantee they rely on.
        self.wait_prefault(timeout=5.0)
        view, self._view = self._view, None
        try:
            if view is not None:
                view.release()
            if self._map is not None:
                self._map.close()
                self._map = None
        except (BufferError, ValueError):
            pass

    def destroy(self):
        self.close()
        self._lib.shm_store_destroy(self.name.encode())


def _encode_header(arr: np.ndarray) -> bytes:
    import json

    meta = json.dumps({"dtype": arr.dtype.str,
                       "shape": list(arr.shape)}).encode()
    return len(meta).to_bytes(4, "little") + meta


def _decode_header(buf):
    import json

    hlen = int.from_bytes(bytes(buf[:4]), "little")
    meta = json.loads(bytes(buf[4:4 + hlen]))
    return np.dtype(meta["dtype"]), tuple(meta["shape"]), 4 + hlen
