"""Tenancy enforcement plane: the pure decision cores.

PR 6 built the *observability* half of multi-tenancy (job tags flow
driver→proxy→router→replica→tasks; per-job CPU-seconds/objects/bytes
are metered). This module is the *enforcement* half — the part that
makes one tenant's flood somebody else's non-problem. Reference roles:
the scheduler-side lease admission policies (`scheduling/policy/`),
Serve's per-application ingress limits, and the plasma arena's
per-client quota accounting.

Design discipline matches ``actor_gate.py``: every class here is pure
decision state — locks and counters, no RPC, no threads, no product
imports — so the bounded model checker (``tools/raymc``
``quota_admission`` scenario) can prove the admission invariants over
every interleaving at small scope, and the product layers wire the
decisions to real effects:

- :class:`QuotaLedger` — per-job resource quotas (CPU slots, concurrent
  leases, queued-task ceiling), checked at lease grant / local dispatch
  (``cluster_utils.ClusterBackendMixin`` + ``local_backend``);
- :class:`FairTaskQueue` — virtual-time weighted fair queuing over the
  scheduler's runnable queue (``local_backend._ready``);
- :class:`FairShare` — the same virtual-time law applied to the serve
  ``Router``'s contended replica slots;
- :class:`TokenBucket` / :class:`IngressLimiter` — per-tenant ingress
  rate limits enforced by ``http_proxy`` before work enters the router;
- arena-budget helpers — per-job shared-segment budgets driving the
  pressure-spill victim order in ``shm_plane``.

Config grammar (see README "Multi-tenancy"):

- ``job_quotas``:   ``"jobA=cpus:2,queued:100,leases:2;jobB=cpus:1"``
- ``job_weights``:  ``"jobA=4,jobB=1"`` (unlisted jobs: ``job_default_weight``)
- ``ingress_rate_limits``: ``"jobA=100:200;jobB=10"`` (rate[:burst] per s)
- ``job_arena_budgets``:   ``"jobA=64m;jobB=268435456"`` (k/m/g suffixes)

Malformed entries are dropped, never fatal — a bad config line must not
take the control plane down (same contract as ``parse_slo_targets``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks
from ray_tpu._private.config import ray_config

# Distinct job ids any one enforcement structure will track (same
# cardinality bound as the proxy's X-Job-Id cap): tags are client- or
# config-controlled, and an attacker cycling tokens must not mint
# unbounded ledger rows or token buckets. Overflow degrades to the
# default (untagged) class.
MAX_TRACKED_JOBS = 512


def quota_counter(kind: str, job: str):
    """``ray_tpu_job_quota_<kind>_total{job}`` after the runtime-metrics
    fold: kind ∈ rejections | parks | lease_denials."""
    return _perf_stats.counter(f"job_quota_{kind}", {"job": job})


def enforcement_enabled() -> bool:
    return bool(ray_config.tenancy_enforcement)


# -- config grammar ----------------------------------------------------------


def _split_entries(raw: str):
    """``"a=...;b=..."`` (``;`` or ``,`` between entries where
    unambiguous — quotas use ``;`` only, simple maps accept both)."""
    for part in raw.replace("\n", ";").split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        job, _, body = part.partition("=")
        job = job.strip()
        if not job:
            continue
        yield job, body.strip()


def parse_bytes(raw: str) -> Optional[int]:
    """``"64m"`` → 67108864; plain ints pass through; None on junk."""
    raw = raw.strip().lower()
    mult = 1
    if raw and raw[-1] in "kmg":
        mult = {"k": 1024, "m": 1024 ** 2, "g": 1024 ** 3}[raw[-1]]
        raw = raw[:-1]
    try:
        n = int(float(raw) * mult)
    except ValueError:
        return None
    return n if n >= 0 else None


@dataclass
class JobQuota:
    """Per-job ceilings; -1 = unlimited. ``cpu_milli`` bounds the job's
    concurrently *running* CPU-slots (milli-CPU, matching the
    scheduler's resource math), ``leases`` its concurrently held
    pipelined dispatch leases, ``queued`` its admitted-but-not-started
    tasks."""

    cpu_milli: int = -1
    leases: int = -1
    queued: int = -1


def parse_job_quotas(raw: Optional[str] = None) -> Dict[str, JobQuota]:
    """``"jobA=cpus:2,queued:100,leases:2;jobB=cpus:1"`` — cpus are
    float CPU slots (converted to milli), queued/leases integer counts.
    Unknown keys and malformed values are dropped."""
    if raw is None:
        raw = ray_config.job_quotas
    out: Dict[str, JobQuota] = {}
    for job, body in _split_entries(raw):
        q = JobQuota()
        valid = False
        for kv in body.split(","):
            key, _, val = kv.strip().partition(":")
            try:
                if key == "cpus":
                    q.cpu_milli = max(0, int(float(val) * 1000))
                elif key == "queued":
                    q.queued = max(0, int(val))
                elif key == "leases":
                    q.leases = max(0, int(val))
                else:
                    continue
                valid = True
            except ValueError:
                continue
        if valid and len(out) < MAX_TRACKED_JOBS:
            out[job] = q
    return out


def parse_job_weights(raw: Optional[str] = None) -> Dict[str, float]:
    """``"jobA=4,jobB=1"`` — weights must be > 0 (a zero weight would
    starve by construction; the non-starvation property only covers
    nonzero-weight classes, so zero is rejected at parse)."""
    if raw is None:
        raw = ray_config.job_weights
    out: Dict[str, float] = {}
    for job, body in _split_entries(raw.replace(",", ";")):
        try:
            w = float(body)
        except ValueError:
            continue
        if w > 0 and len(out) < MAX_TRACKED_JOBS:
            out[job] = w
    return out


# Weights are read per served item on the dispatch hot path: cache the
# parse keyed on the config string (replaced wholesale on change, never
# grown).
_weights_cache: Tuple[Optional[str], Dict[str, float]] = (None, {})


def cached_job_weights() -> Dict[str, float]:
    global _weights_cache
    raw = ray_config.job_weights
    if raw != _weights_cache[0]:
        _weights_cache = (raw, parse_job_weights(raw))
    return _weights_cache[1]


def parse_rate_limits(raw: Optional[str] = None) \
        -> Dict[str, Tuple[float, float]]:
    """``"jobA=100:200;jobB=10"`` → {job: (rate_per_s, burst)}; burst
    defaults to the rate."""
    if raw is None:
        raw = ray_config.ingress_rate_limits
    out: Dict[str, Tuple[float, float]] = {}
    for job, body in _split_entries(raw):
        rate_s, _, burst_s = body.partition(":")
        try:
            rate = float(rate_s)
            burst = float(burst_s) if burst_s else rate
        except ValueError:
            continue
        if rate > 0 and burst > 0 and len(out) < MAX_TRACKED_JOBS:
            out[job] = (rate, burst)
    return out


def parse_arena_budgets(raw: Optional[str] = None) -> Dict[str, int]:
    """``"jobA=64m;jobB=268435456"`` → {job: budget_bytes}."""
    if raw is None:
        raw = ray_config.job_arena_budgets
    out: Dict[str, int] = {}
    for job, body in _split_entries(raw):
        n = parse_bytes(body)
        if n is not None and n > 0 and len(out) < MAX_TRACKED_JOBS:
            out[job] = n
    return out


# -- quota ledger ------------------------------------------------------------


class QuotaLedger:
    """Per-job admission + usage accounting: the ONE structure both the
    head's lease path and the local backend's dispatch gate consult, so
    a job's cluster-wide CPU-slot usage is a single number no matter
    where its tasks land.

    Charge tokens ride the spec itself (``spec._quota_cpu`` /
    ``spec._quota_queued``): every acquire is idempotent per spec and
    every release clears the token, so a spec that crosses layers
    (parked → resubmitted → leased → replayed after a node death) is
    charged exactly once at a time regardless of which layer releases
    it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._changed = threading.Condition(self._lock)
        self._quotas: Dict[str, JobQuota] = {}
        self._src: Optional[str] = None
        self._cpu: Dict[str, int] = {}      # running milli-CPU by job
        self._peak_cpu: Dict[str, int] = {}  # high-water mark (proofs)
        self._queued: Dict[str, int] = {}
        self._leases: Dict[str, int] = {}
        # Specs parked because their job is at its CPU quota, FIFO per
        # job; a single drainer thread (the owner's) resubmits them as
        # capacity frees. Pure state here — the park/drain effects are
        # the caller's.
        self._parked: Dict[str, List] = {}
        # A node process must NOT re-enforce quotas the head already
        # applied at grant time (per-node enforcement of a cluster-wide
        # quota would be wrong twice over).
        self._disabled = False

    # -- configuration ---------------------------------------------------

    def disable(self) -> None:
        self._disabled = True

    def _active_quota(self, job: str) -> Optional[JobQuota]:
        """The job's quota when enforcement is live, else None. Re-parses
        when the config string changed (tests flip it at runtime)."""
        if self._disabled or not enforcement_enabled():
            return None
        raw = ray_config.job_quotas
        if raw != self._src:
            with self._lock:
                if raw != self._src:
                    self._quotas = parse_job_quotas(raw)
                    self._src = raw
        return self._quotas.get(job)

    # -- queued-task ceiling ---------------------------------------------

    def note_queued(self, spec) -> Optional[str]:
        """Admission: None = admitted (queued count charged to the
        spec), else the rejection reason (the queued-task ceiling is
        the job's own submit-flood bound). Idempotent per spec —
        resubmits/replays keep their original admission."""
        if getattr(spec, "_quota_queued", None) is not None:
            return None
        if getattr(spec, "_quota_admitted", False) or \
                getattr(spec, "attempt", 0) > 0 or \
                getattr(spec, "restarts_used", 0) > 0:
            # A retry of ACCEPTED work must never bounce off the
            # ceiling its own job's flood filled: the sticky admitted
            # flag covers every resubmit flavor (lease reroutes,
            # retry_exceptions retries), attempt covers node-death
            # replays, restarts_used covers actor-restart creation
            # resubmits (a bounced restart would strand the gate in
            # RESTARTING).
            return None
        job = getattr(spec, "job_id", "") or ""
        quota = self._active_quota(job)
        if quota is None or quota.queued < 0:
            return None
        sanitize_hooks.spec_op("spec.quota.admit", "call", self,
                               (job, quota.queued))
        reason = None
        with self._lock:
            have = self._queued.get(job, 0)
            if have >= quota.queued:
                quota_counter("rejections", job).inc()
                reason = (f"job {job!r} is at its queued-task ceiling "
                          f"({have} queued, quota queued:{quota.queued}) "
                          f"— submit rejected; release or await existing "
                          f"work, or raise job_quotas for this job")
            else:
                self._queued[job] = have + 1
        sanitize_hooks.spec_op("spec.quota.admit", "ret", self,
                               reason is None)
        if reason is not None:
            return reason
        spec._quota_queued = job
        spec._quota_admitted = True
        return None

    def note_dequeued(self, spec) -> None:
        """The spec left the queue (dispatched or reached a terminal
        error): release its queued-ceiling charge."""
        job = getattr(spec, "_quota_queued", None)
        if job is None:
            return
        spec._quota_queued = None
        sanitize_hooks.spec_op("spec.quota.dequeue", "call", self, job)
        with self._lock:
            left = self._queued.get(job, 0) - 1
            if left > 0:
                self._queued[job] = left
            else:
                self._queued.pop(job, None)
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.quota.dequeue", "ret", self, None)

    # -- CPU slots -------------------------------------------------------

    def try_acquire_cpu(self, spec, milli: Optional[int] = None) -> bool:
        """Charge the spec's CPU request against its job's quota; False
        when the job is at its cap (the caller parks the spec behind
        the job's own limit). Specs of jobs with no quota — and specs
        already charged — pass for free."""
        if getattr(spec, "_quota_cpu", None) is not None:
            return True
        job = getattr(spec, "job_id", "") or ""
        quota = self._active_quota(job)
        if quota is None or quota.cpu_milli < 0:
            return True
        if milli is None:
            milli = int((spec.resources or {}).get("CPU", 0) * 1000)
        if milli <= 0:
            return True  # zero-CPU work never counts against CPU slots
        sanitize_hooks.spec_op("spec.quota.charge", "call", self,
                               (job, milli, quota.cpu_milli))
        sanitize_hooks.sched_point("tenancy.acquire")
        ok = True
        with self._lock:
            used = self._cpu.get(job, 0)
            if used + milli > quota.cpu_milli:
                ok = False
            else:
                self._cpu[job] = used + milli
                if used + milli > self._peak_cpu.get(job, 0):
                    self._peak_cpu[job] = used + milli
        sanitize_hooks.spec_op("spec.quota.charge", "ret", self, ok)
        if ok:
            spec._quota_cpu = (job, milli)
        return ok

    def release_cpu(self, spec) -> None:
        """Release the spec's CPU charge (terminal state or node-death
        resubmit boundary). Idempotent — the token clears on first
        release."""
        token = getattr(spec, "_quota_cpu", None)
        if token is None:
            return
        spec._quota_cpu = None
        job, milli = token
        sanitize_hooks.spec_op("spec.quota.release", "call", self,
                               (job, milli))
        sanitize_hooks.sched_point("tenancy.release")
        with self._lock:
            left = self._cpu.get(job, 0) - milli
            if left > 0:
                self._cpu[job] = left
            else:
                self._cpu.pop(job, None)
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.quota.release", "ret", self, None)

    # -- concurrent leases -----------------------------------------------

    def try_acquire_lease(self, job: str) -> bool:
        quota = self._active_quota(job or "")
        if quota is None or quota.leases < 0:
            return True
        sanitize_hooks.spec_op("spec.quota.lease_acquire", "call", self,
                               (job, quota.leases))
        ok = True
        with self._lock:
            have = self._leases.get(job, 0)
            if have >= quota.leases:
                quota_counter("lease_denials", job).inc()
                ok = False
            else:
                self._leases[job] = have + 1
        sanitize_hooks.spec_op("spec.quota.lease_acquire", "ret", self, ok)
        return ok

    def release_lease(self, job: str) -> None:
        sanitize_hooks.spec_op("spec.quota.lease_release", "call", self,
                               job)
        with self._lock:
            left = self._leases.get(job, 0) - 1
            if left > 0:
                self._leases[job] = left
            else:
                self._leases.pop(job, None)
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.quota.lease_release", "ret", self,
                               None)

    # -- quota parking (over-CPU-quota specs wait HERE, not in the
    #    scheduler, so they consume no cluster capacity) -----------------

    def park(self, spec) -> None:
        job = getattr(spec, "job_id", "") or ""
        quota_counter("parks", job).inc()
        sanitize_hooks.sched_point("tenancy.park")
        with self._lock:
            self._parked.setdefault(job, []).append(spec)
            self._changed.notify_all()  # wake the drainer to (re)arm

    def parked_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._parked.values())

    def take_dispatchable(self) -> List:
        """Pop every parked spec whose job now has CPU headroom,
        charging each under the lock (check + charge are atomic — two
        drain passes must not both dispatch into the last slot).
        Called by the owner's single drainer thread."""
        sanitize_hooks.spec_op("spec.quota.drain", "call", self, None)
        out: List = []
        charged: List[Tuple[str, int, int]] = []
        with self._lock:
            for job in list(self._parked):
                quota = self._quotas.get(job)
                specs = self._parked[job]
                while specs:
                    spec = specs[0]
                    milli = int((spec.resources or {}).get(
                        "CPU", 0) * 1000)
                    if quota is not None and quota.cpu_milli >= 0 \
                            and milli > 0:
                        used = self._cpu.get(job, 0)
                        if used + milli > quota.cpu_milli:
                            break
                        self._cpu[job] = used + milli
                        if used + milli > self._peak_cpu.get(job, 0):
                            self._peak_cpu[job] = used + milli
                        spec._quota_cpu = (job, milli)
                        charged.append((job, milli, quota.cpu_milli))
                    out.append(specs.pop(0))
                if not specs:
                    del self._parked[job]
        sanitize_hooks.spec_op("spec.quota.drain", "ret", self, charged)
        return out

    def wait_change(self, timeout_s: float) -> None:
        with self._changed:
            self._changed.wait(timeout_s)

    # -- introspection ---------------------------------------------------

    def usage(self, job: str) -> Dict[str, int]:
        with self._lock:
            return {
                "cpu_milli": self._cpu.get(job, 0),
                "peak_cpu_milli": self._peak_cpu.get(job, 0),
                "queued": self._queued.get(job, 0),
                "leases": self._leases.get(job, 0),
                "parked": len(self._parked.get(job, ())),
            }

    def jobs(self) -> List[str]:
        with self._lock:
            # _peak_cpu included: a job whose usage drained back to
            # zero keeps its high-water row — the peak is the "never
            # exceeded the quota" proof artifact job_summary shows.
            keys = set(self._cpu) | set(self._queued) | \
                set(self._leases) | set(self._parked) | \
                set(self._peak_cpu)
        return sorted(keys)


# -- weighted fair queuing ---------------------------------------------------


class FairTaskQueue:
    """Drop-in for the scheduler's runnable ``queue.Queue`` with
    per-job virtual-time WFQ ordering.

    Classic virtual-finish-time law: each class (job) carries a virtual
    time advanced by ``cost/weight`` per served item; ``get`` serves
    the backlogged class with the smallest virtual time. A class
    joining an ongoing schedule starts at the global virtual time (no
    credit for having been idle). With enforcement off — or every item
    untagged — everything lands in one class and the queue is exactly
    the FIFO it replaces.

    ``max_bypass`` is the proven non-starvation witness: how many
    consecutive serves ever bypassed a backlogged class. Under the WFQ
    law a backlogged class of weight w is served at least once per
    ceil(total_weight/w) serves; the raymc ``quota_admission`` scenario
    checks the bound over every bounded interleaving.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._weights = weights  # None = read from config per put
        self._classes: Dict[str, List] = {}   # job -> FIFO list
        self._vt: Dict[str, float] = {}       # per-class virtual time
        self._global_vt = 0.0
        self._count = 0
        self._bypass: Dict[str, int] = {}     # consecutive bypasses
        self.max_bypass = 0

    def _weight(self, job: str) -> float:
        weights = self._weights
        if weights is None:
            weights = cached_job_weights()
        return weights.get(job) or max(
            float(ray_config.job_default_weight), 1e-6)

    def _class_of(self, item) -> str:
        if self._weights is None and not enforcement_enabled():
            return ""  # enforcement off: one class, pure FIFO
        return getattr(item, "job_id", "") or ""

    def put(self, item) -> None:
        job = self._class_of(item)
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.put", "call", self, (job, item))
        with self._cond:
            q = self._classes.get(job)
            if q is None:
                q = self._classes[job] = []
            if not q:
                # (Re)joining: start at the global virtual time — an
                # idle class accrues no credit it could burst on.
                self._vt[job] = max(self._vt.get(job, 0.0),
                                    self._global_vt)
            q.append(item)
            self._count += 1
            self._cond.notify()
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.put", "ret", self, None)

    def _pop_locked(self):
        best, best_vt = None, 0.0
        for job, q in self._classes.items():
            if not q:
                continue
            vt = self._vt.get(job, 0.0)
            if best is None or vt < best_vt:
                best, best_vt = job, vt
        if best is None:
            return None
        # Non-starvation bookkeeping: every backlogged class NOT served
        # by this pop was bypassed once; the served class resets.
        for job, q in self._classes.items():
            if not q:
                continue
            if job == best:
                self._bypass[job] = 0
            else:
                n = self._bypass.get(job, 0) + 1
                self._bypass[job] = n
                if n > self.max_bypass:
                    self.max_bypass = n
        q = self._classes[best]
        item = q.pop(0)
        self._count -= 1
        self._global_vt = best_vt
        self._vt[best] = best_vt + 1.0 / self._weight(best)
        if not q:
            del self._classes[best]
            self._bypass.pop(best, None)
            # Cardinality bound: job ids are caller-controlled, and a
            # per-submission id would otherwise mint a permanent _vt
            # row. Dropping an EMPTY class's clock is safe — on
            # rejoin it starts at the global virtual time, exactly
            # like a new class.
            if len(self._vt) > MAX_TRACKED_JOBS:
                for stale in [j for j in self._vt
                              if j not in self._classes]:
                    del self._vt[stale]
        return item

    def get(self, timeout: Optional[float] = None):
        import queue as _queue

        # The pop tap's result payload is the served item, None for an
        # empty (timed-out) beat — items are specs/headers, never None.
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.pop", "call", self, None)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            while self._count == 0:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    if sanitize_hooks.spec_taps_active:
                        sanitize_hooks.spec_op("spec.wfq.pop", "ret", self,
                                               None)
                    raise _queue.Empty
                self._cond.wait(remaining)
            item = self._pop_locked()
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.pop", "ret", self, item)
        return item

    def get_nowait(self):
        import queue as _queue

        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.pop", "call", self, None)
        with self._cond:
            if self._count == 0:
                if sanitize_hooks.spec_taps_active:
                    sanitize_hooks.spec_op("spec.wfq.pop", "ret", self, None)
                raise _queue.Empty
            item = self._pop_locked()
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.wfq.pop", "ret", self, item)
        return item

    def qsize(self) -> int:
        with self._lock:
            return self._count

    def empty(self) -> bool:
        return self.qsize() == 0


class FairShare:
    """Virtual-time fair arbitration over the serve router's contended
    replica slots. The router has no queue to reorder — waiting
    requests poll for a slot — so fairness is a *turn gate*: a dispatch
    may proceed only when its job's virtual time is minimal among the
    jobs currently waiting. Each successful dispatch advances the
    job's virtual time by 1/weight, so a flood job's turns thin out to
    its weight share while a high-weight tenant's stay dense.

    With enforcement off (or no waiters) every dispatch passes — the
    gate costs one lock acquisition on the contended path only.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self._lock = threading.Lock()
        self._weights = weights
        self._vt: Dict[str, float] = {}
        self._global_vt = 0.0
        self._waiting: Dict[str, int] = {}

    def _weight(self, job: str) -> float:
        weights = self._weights
        if weights is None:
            weights = cached_job_weights()
        return weights.get(job) or max(
            float(ray_config.job_default_weight), 1e-6)

    def enter_wait(self, job: str) -> None:
        with self._lock:
            self._waiting[job] = self._waiting.get(job, 0) + 1
            if self._waiting[job] == 1:
                self._vt[job] = max(self._vt.get(job, 0.0),
                                    self._global_vt)

    def exit_wait(self, job: str) -> None:
        with self._lock:
            left = self._waiting.get(job, 0) - 1
            if left > 0:
                self._waiting[job] = left
            else:
                self._waiting.pop(job, None)

    def may_dispatch(self, job: str) -> bool:
        """True when no other waiting job has a strictly smaller
        virtual time (ties pass — the replica cap, not this gate, is
        the concurrency bound)."""
        if self._weights is None and not enforcement_enabled():
            return True
        with self._lock:
            if not self._waiting:
                return True
            mine = max(self._vt.get(job, 0.0), self._global_vt) \
                if job not in self._waiting else self._vt.get(job, 0.0)
            return all(self._vt.get(other, 0.0) >= mine
                       for other in self._waiting if other != job)

    def charge(self, job: str) -> None:
        """A dispatch happened: advance the job's virtual time by its
        inverse weight."""
        if self._weights is None and not enforcement_enabled():
            return
        with self._lock:
            vt = max(self._vt.get(job, 0.0), self._global_vt)
            self._global_vt = vt
            self._vt[job] = vt + 1.0 / self._weight(job)
            # Cardinality bound (job tags are caller-controlled): drop
            # non-waiting clocks at or behind the global time — a
            # dropped job re-enters at the global clock, same as new.
            if len(self._vt) > MAX_TRACKED_JOBS:
                for stale in [j for j, v in self._vt.items()
                              if j not in self._waiting
                              and v <= self._global_vt]:
                    del self._vt[stale]
            # Bound float growth on long-lived routers: rebase when the
            # clock runs far ahead (relative order is all that matters).
            if self._global_vt > 1e12:
                base = min(self._vt.values(), default=0.0)
                self._global_vt -= base
                for k in self._vt:
                    self._vt[k] -= base


# -- ingress token buckets ---------------------------------------------------


class TokenBucket:
    """Classic token bucket; ``clock`` injectable for deterministic
    tests. Not thread-safe on its own — :class:`IngressLimiter` holds
    the lock (and the proxy calls from one loop thread anyway)."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = time.monotonic() if now is None else now

    def try_take(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        if now > self.last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
        self.last = max(self.last, now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def retry_after_s(self) -> float:
        """Seconds until one token accrues (the 429 Retry-After hint)."""
        if self.tokens >= 1.0 or self.rate <= 0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


class IngressLimiter:
    """Per-tenant token buckets for the HTTP ingress. Buckets are
    minted per distinct job tag up to :data:`MAX_TRACKED_JOBS`;
    overflow tags share the default bucket (the cardinality contract
    the X-Job-Id cap established). A job with no configured limit —
    and no default rate — is never limited."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._clock = clock or time.monotonic
        self._buckets: Dict[str, TokenBucket] = {}
        self._limits: Dict[str, Tuple[float, float]] = {}
        self._src: Optional[str] = None

    def _limit_for(self, job: str) -> Optional[Tuple[float, float]]:
        raw = ray_config.ingress_rate_limits
        if raw != self._src:
            self._limits = parse_rate_limits(raw)
            self._src = raw
            # Minted buckets carry their creation-time rate/burst:
            # drop them on a config change so an operator's runtime
            # limit adjustment actually takes effect (buckets restart
            # at full burst — a one-off grace, not a leak).
            self._buckets.clear()
        limit = self._limits.get(job)
        if limit is not None:
            return limit
        rate = float(ray_config.ingress_default_rate_per_s)
        if rate <= 0:
            return None
        burst = float(ray_config.ingress_default_burst) or rate
        return (rate, burst)

    def try_admit(self, job: str) -> Optional[float]:
        """None = admitted; else seconds to wait (the Retry-After
        payload for the 429)."""
        if not enforcement_enabled():
            return None
        job = job or ""
        with self._lock:
            limit = self._limit_for(job)
            if limit is None:
                return None
            bucket = self._buckets.get(job)
            if bucket is None:
                if len(self._buckets) >= MAX_TRACKED_JOBS:
                    # Cardinality guard: overflow tags share the
                    # DEFAULT class's bucket — limit re-resolved for
                    # "" so the shared bucket never inherits whichever
                    # overflow job's limit happened to arrive first.
                    job = ""
                    limit = self._limit_for(job)
                    if limit is None:
                        return None
                    bucket = self._buckets.get(job)
                if bucket is None:
                    bucket = self._buckets[job] = TokenBucket(
                        limit[0], limit[1], now=self._clock())
            if bucket.try_take(self._clock()):
                return None
            _perf_stats.counter("job_rate_limited", {"job": job}).inc()
            return max(bucket.retry_after_s(), 0.001)


# -- serve priority classes --------------------------------------------------


# Ordinal priority classes for the serve ingress (X-Priority header):
# index IS the shed order — higher index sheds first.
PRIORITY_CLASSES = ("high", "normal", "low")
_PRIORITY_BY_NAME = {name: i for i, name in enumerate(PRIORITY_CLASSES)}
_PRIORITY_DEFAULT = 1  # normal


def parse_priority(raw: str) -> int:
    """``X-Priority`` header value → class index. Accepts the class
    names or their ordinals; anything else — including absence — is
    ``normal`` (a malformed client header must neither crash nor grant
    elevated priority)."""
    raw = (raw or "").strip().lower()
    if not raw:
        return _PRIORITY_DEFAULT
    idx = _PRIORITY_BY_NAME.get(raw)
    if idx is not None:
        return idx
    if raw.isdigit():
        n = int(raw)
        if n < len(PRIORITY_CLASSES):
            return n
    return _PRIORITY_DEFAULT


def parse_shed_fractions(raw: Optional[str] = None) -> Tuple[float, ...]:
    """``serve_priority_shed_fractions`` (``"1.0,1.0,0.5"``) → one
    admission fraction per priority class. Malformed / missing entries
    fall back to 1.0 (never shed below the hard cap) — a config typo
    must not start shedding traffic."""
    if raw is None:
        raw = ray_config.serve_priority_shed_fractions
    out = [1.0] * len(PRIORITY_CLASSES)
    for i, part in enumerate((raw or "").split(",")):
        if i >= len(out):
            break
        try:
            val = float(part.strip())
        except ValueError:
            continue
        if 0.0 <= val <= 1.0:
            out[i] = val
    return tuple(out)


class PriorityGate:
    """Priority-class load shedding for the HTTP ingress: the decision
    half of "shed lowest class first".

    Two independent admission checks, both cheap enough for the
    per-request fast path:

    - **layered thresholds**: class ``c`` is admitted while the proxy's
      in-flight count is below ``capacity * fraction[c]`` — as load
      rises, ``low`` sheds first, then ``normal``, and ``high`` rides
      to the hard cap (fraction defaults keep high/normal at 1.0, so
      untagged traffic behaves exactly as before priorities existed);
    - **per-class token buckets** (``serve_priority_rates``,
      ``"low=50:100"``): a class over its configured rate sheds even
      with in-flight headroom — the knob that keeps a background-class
      flood from consuming the headroom bursts need.

    Returns the Retry-After seconds on shed (the 503 honors it), None
    on admit. Unlike the tenancy quota plane this is always on — it is
    data-plane overload protection, not multi-tenant policy — but the
    default config is behavior-neutral for high/normal traffic.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._clock = clock or time.monotonic
        self._fractions_src: Optional[str] = None
        self._fractions: Tuple[float, ...] = (1.0,) * len(PRIORITY_CLASSES)
        self._rates_src: Optional[str] = None
        self._buckets: Dict[int, TokenBucket] = {}

    def _refresh_locked(self) -> None:
        raw = ray_config.serve_priority_shed_fractions
        if raw != self._fractions_src:
            self._fractions = parse_shed_fractions(raw)
            self._fractions_src = raw
        raw = ray_config.serve_priority_rates
        if raw != self._rates_src:
            limits = parse_rate_limits(raw)
            self._buckets = {
                _PRIORITY_BY_NAME[name]: TokenBucket(rate, burst,
                                                     now=self._clock())
                for name, (rate, burst) in limits.items()
                if name in _PRIORITY_BY_NAME
            }
            self._rates_src = raw

    def try_admit(self, cls: int, in_flight: int,
                  capacity: int) -> Optional[float]:
        """None = admitted; else seconds to wait before retrying (the
        503's Retry-After). ``cls`` is the :func:`parse_priority`
        index; out-of-range values are clamped to the lowest class."""
        cls = min(max(cls, 0), len(PRIORITY_CLASSES) - 1)
        with self._lock:
            self._refresh_locked()
            frac = self._fractions[cls]
            if frac < 1.0 and in_flight >= capacity * frac:
                _perf_stats.counter(
                    "serve_priority_shed",
                    {"class": PRIORITY_CLASSES[cls]}).inc()
                return 1.0
            bucket = self._buckets.get(cls)
            if bucket is not None and not bucket.try_take(self._clock()):
                _perf_stats.counter(
                    "serve_priority_shed",
                    {"class": PRIORITY_CLASSES[cls]}).inc()
                return max(bucket.retry_after_s(), 0.001)
        return None


# -- arena budgets -----------------------------------------------------------


def arena_spill_counter(job: str):
    """``ray_tpu_job_arena_spill_bytes_total{job}``: bytes the pressure
    sweep spilled out of the arena charged to this job — the 'your 256MB
    objects hit YOUR budget' signal in job_summary and the dashboards."""
    return _perf_stats.counter("job_arena_spill_bytes", {"job": job})


def over_budget_jobs(usage: Dict[str, int],
                     budgets: Optional[Dict[str, int]] = None) -> set:
    """Jobs whose charged arena bytes exceed their configured budget
    (jobs without a budget are never 'over')."""
    if budgets is None:
        budgets = parse_arena_budgets()
    if not budgets or not enforcement_enabled():
        return set()
    return {job for job, used in usage.items()
            if job in budgets and used > budgets[job]}


def order_spill_victims(candidates: List[bytes],
                        job_of: Callable[[bytes], str],
                        over: set) -> List[bytes]:
    """Pressure-spill victim order: the over-budget jobs' objects first
    (cold-first within each tier — the input is already oldest-first),
    so one tenant's oversized working set spills ITSELF before it can
    evict anyone else's."""
    if not over:
        return candidates
    first = [ob for ob in candidates if job_of(ob) in over]
    rest = [ob for ob in candidates if job_of(ob) not in over]
    return first + rest
