"""Shared-memory object plane: large values cross process boundaries
through the native store, not pickle-over-TCP.

Role-equivalent to the reference's plasma integration
(`src/ray/core_worker/store_provider/plasma_store_provider.h`): values
whose payload exceeds a threshold are serialized once into the node's
shm segment (`src/object_store/store.cc`) with pickle protocol 5 —
array buffers go out-of-band, so a reader on the same host reconstructs
numpy arrays as zero-copy views over the mapped segment.

Lifecycle: readers pin objects on get (store refcount) and the pin is
released when the local MemoryStore entry is dropped — i.e. zero-copy
views are valid while an ObjectRef is in scope, the reference's
documented contract for plasma-backed numpy. Creates that fail for lack
of space retry after waiting out eviction (the reference's
create-request-queue backpressure, `plasma/create_request_queue.h`),
then fall back to the heap/RPC path.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Optional, Tuple

import cloudpickle

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import ShmObjectStore

_MAGIC = b"RTS1"
_ALIGN = 64

DEFAULT_THRESHOLD = int(os.environ.get("RAY_TPU_SHM_THRESHOLD", 64 * 1024))
DEFAULT_CAPACITY = int(os.environ.get("RAY_TPU_SHM_CAPACITY",
                                      1024 * 2**20))


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SharedPlane:
    """One process's handle onto the node-wide shared object segment."""

    def __init__(self, name: str, *, create: bool,
                 capacity: int = DEFAULT_CAPACITY,
                 max_objects: int = 8192,
                 threshold: int = DEFAULT_THRESHOLD):
        self.name = name
        self.threshold = threshold
        self.store = ShmObjectStore(name=name, capacity=capacity,
                                    max_objects=max_objects, create=create)
        self._lock = threading.Lock()
        self._pinned: set[bytes] = set()
        self._owner = create

    # -- write side ------------------------------------------------------

    def maybe_put(self, object_id: ObjectID, value: Any,
                  timeout: float = 2.0) -> bool:
        """Serialize ``value`` into the segment if its payload crosses the
        threshold. Returns True iff the object is now readable from shm."""
        # Cheap pre-screen: obviously-small values skip the pickle-to-
        # measure step entirely (pickling every int/str task result just
        # to learn it's under the threshold dominated small-task runs).
        if value is None or isinstance(value, (bool, int, float)):
            return False
        if isinstance(value, (str, bytes, bytearray)) and \
                len(value) < self.threshold:
            return False
        oid = object_id.binary()
        if self.store.contains(oid):
            return True
        try:
            buffers: list = []
            pik = cloudpickle.dumps(value, protocol=5,
                                    buffer_callback=buffers.append)
            raws = [b.raw() for b in buffers]
        except Exception:
            return False  # unpicklable / non-contiguous buffer: heap path
        total_payload = len(pik) + sum(r.nbytes for r in raws)
        if total_payload < self.threshold:
            return False

        # Layout: magic | u32 npickle | u32 nbuffers |
        #         nbuffers * (u64 offset, u64 length) | pickle | buffers
        header_len = len(_MAGIC) + 8 + 16 * len(raws)
        pik_off = header_len
        offs = []
        cursor = _align(pik_off + len(pik))
        for r in raws:
            offs.append((cursor, r.nbytes))
            cursor = _align(cursor + r.nbytes)
        total = cursor

        deadline = time.monotonic() + timeout
        while True:
            off = self.store._lib.shm_obj_create(
                self.store._handle, oid, total)
            if off != 2**64 - 1:
                break
            # Create-queue backpressure: eviction may need releases from
            # other processes; wait briefly and retry.
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

        view = self.store._view
        cur = off
        view[cur:cur + len(_MAGIC)] = _MAGIC
        cur += len(_MAGIC)
        view[cur:cur + 4] = len(pik).to_bytes(4, "little")
        view[cur + 4:cur + 8] = len(raws).to_bytes(4, "little")
        cur += 8
        for boff, blen in offs:
            view[cur:cur + 8] = boff.to_bytes(8, "little")
            view[cur + 8:cur + 16] = blen.to_bytes(8, "little")
            cur += 16
        view[off + pik_off:off + pik_off + len(pik)] = pik
        for (boff, blen), r in zip(offs, raws):
            if blen:
                view[off + boff:off + boff + blen] = r.cast("B")
        return bool(self.store._lib.shm_obj_seal(self.store._handle, oid))

    # -- read side -------------------------------------------------------

    def get(self, object_id: ObjectID) -> Tuple[bool, Any]:
        """(found, value). Arrays in the value are zero-copy views over
        the segment; the object stays pinned until `release`."""
        oid = object_id.binary()
        buf = self.store.get_bytes(oid)  # pins on success
        if buf is None:
            return False, None
        try:
            if bytes(buf[:4]) != _MAGIC:
                self.store.release(oid)
                return False, None
            npik = int.from_bytes(bytes(buf[4:8]), "little")
            nbuf = int.from_bytes(bytes(buf[8:12]), "little")
            cur = 12
            offs = []
            for _ in range(nbuf):
                boff = int.from_bytes(bytes(buf[cur:cur + 8]), "little")
                blen = int.from_bytes(bytes(buf[cur + 8:cur + 16]),
                                      "little")
                offs.append((boff, blen))
                cur += 16
            pik = bytes(buf[cur:cur + npik])
            base = self.store._view
            # Offsets are relative to the object payload; rebase onto the
            # process-wide mapping so views outlive `buf`.
            obj_off = self._payload_offset(oid)
            # Read-only views: sealed objects are immutable; a writable
            # reconstructed array would let readers corrupt shared memory.
            views = [base[obj_off + boff:obj_off + boff + blen]
                     .toreadonly() for boff, blen in offs]
            value = pickle.loads(pik, buffers=views)
        except Exception:
            self.store.release(oid)
            raise
        with self._lock:
            if oid in self._pinned:
                # Already pinned by an earlier get: drop the extra pin.
                self.store.release(oid)
            else:
                self._pinned.add(oid)
        return True, value

    def _payload_offset(self, oid: bytes) -> int:
        import ctypes

        size = ctypes.c_uint64()
        off = self.store._lib.shm_obj_get(self.store._handle, oid,
                                          ctypes.byref(size))
        if off == 2**64 - 1:
            raise KeyError("object vanished from shm store")
        self.store.release(oid)  # balance the extra pin from the lookup
        return off

    def contains(self, object_id: ObjectID) -> bool:
        return self.store.contains(object_id.binary())

    def release(self, object_id: ObjectID) -> None:
        oid = object_id.binary()
        with self._lock:
            if oid not in self._pinned:
                return
            self._pinned.discard(oid)
        self.store.release(oid)

    def stats(self) -> dict:
        return self.store.stats()

    # -- lifecycle -------------------------------------------------------

    def install(self, worker) -> None:
        """Attach this plane to a Worker: large puts/outputs get shared,
        and MemoryStore entry GC releases shm pins."""
        worker.shm_plane = self
        store = worker.memory_store
        plane = self

        orig_remove = store.remove_local_ref

        def remove_local_ref(object_id):
            entry = store._entries.get(object_id)
            last = entry is not None and entry.local_refs <= 1
            zero = orig_remove(object_id)
            if last and object_id not in store._entries:
                plane.release(object_id)
            return zero  # the became-zero signal drives cluster release

        store.remove_local_ref = remove_local_ref

    def close(self):
        with self._lock:
            pinned, self._pinned = list(self._pinned), set()
        for oid in pinned:
            try:
                self.store.release(oid)
            except Exception:
                pass
        self.store.close()

    def destroy(self, unmap: bool = True):
        """Tear the segment down. ``unmap=False`` unlinks the name but
        leaves the mapping intact: in-flight readers on other threads
        (driver fetch loops during cluster shutdown) would otherwise
        fault on unmapped memory; the pages free at process exit."""
        if unmap:
            self.close()
        else:
            with self._lock:
                pinned, self._pinned = list(self._pinned), set()
            for oid in pinned:
                try:
                    self.store.release(oid)
                except Exception:
                    pass
            self.store.stop_transfer_server()
        try:
            self.store._lib.shm_store_destroy(self.name.encode())
        except Exception:
            pass


def share_value(worker, object_id: ObjectID, value: Any) -> bool:
    """Publish a worker-local value into the node's shared plane (no-op
    without a plane or for small values)."""
    plane: Optional[SharedPlane] = getattr(worker, "shm_plane", None)
    if plane is None or value is None:
        return False
    try:
        return plane.maybe_put(object_id, value)
    except Exception:
        return False
