"""Shared-memory object plane: large values cross process boundaries
through the native store, not pickle-over-TCP.

Role-equivalent to the reference's plasma integration
(`src/ray/core_worker/store_provider/plasma_store_provider.h`): values
whose payload exceeds a threshold are serialized once into the node's
shm segment (`src/object_store/store.cc`) with pickle protocol 5 —
array buffers go out-of-band, so a reader on the same host reconstructs
numpy arrays as zero-copy views over the mapped segment.

This is the DEFAULT large-object path, not a best-effort probe: task
outputs are published here and the producer's heap entry is swapped to
the zero-copy shm view (`publish_task_output`), so a large value lives
ONCE — in the arena — instead of heap+arena; the control plane then
moves `wire.ObjectDescriptor`s (segment name, transfer endpoint, size)
instead of pickled payloads whenever both ends can reach a segment.

Lifecycle: readers pin objects on get (store refcount) and the pin is
released when the local MemoryStore entry is dropped — i.e. zero-copy
views are valid while an ObjectRef is in scope, the reference's
documented contract for plasma-backed numpy. Creates that fail for lack
of space first spill the owner's cold, otherwise-unpinned objects to
disk (URL on the store entry, transparent restore on get — the
reference's LocalObjectManager spill pipeline applied to the arena),
then wait out cross-process eviction, then fall back to the heap/RPC
path.
"""

from __future__ import annotations

import collections
import os
import pickle
import threading
import time
from typing import Any, Optional, Tuple

import cloudpickle

from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import tenancy
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import ShmObjectStore

_MAGIC = b"RTS1"
_ALIGN = 64

DEFAULT_THRESHOLD = int(os.environ.get("RAY_TPU_SHM_THRESHOLD", 64 * 1024))
DEFAULT_CAPACITY = int(os.environ.get("RAY_TPU_SHM_CAPACITY",
                                      1024 * 2**20))

# Object-plane observability (satellite of the bandwidth overhaul):
# folded into /api/metrics as ray_tpu_object_* series by
# runtime_metrics._collect_fastpath_stats, node-tagged on the head's
# merged exposition via the PR 3 snapshot-shipping plane.
_BACKPRESSURE_WAITS = _perf_stats.counter("object_create_backpressure_waits")
_SHM_SPILLS = _perf_stats.counter("object_shm_spills")


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _parse_header(buf):
    """(pickle_bytes, [(offset, length)]) from an RTS1 payload header.
    Offsets are relative to the payload start."""
    if bytes(buf[:4]) != _MAGIC:
        return None, None
    npik = int.from_bytes(bytes(buf[4:8]), "little")
    nbuf = int.from_bytes(bytes(buf[8:12]), "little")
    cur = 12
    offs = []
    for _ in range(nbuf):
        boff = int.from_bytes(bytes(buf[cur:cur + 8]), "little")
        blen = int.from_bytes(bytes(buf[cur + 8:cur + 16]), "little")
        offs.append((boff, blen))
        cur += 16
    return bytes(buf[cur:cur + npik]), offs


def decode_payload(raw) -> Any:
    """Reconstruct a value from a self-contained RTS1 payload (a spilled
    copy read back from disk). Array buffers view ``raw`` — immutable
    and kept alive by the arrays' base reference."""
    pik, offs = _parse_header(raw)
    if pik is None:
        raise ValueError("not an RTS1 object payload")
    mv = memoryview(raw)
    views = [mv[boff:boff + blen] for boff, blen in offs]
    return pickle.loads(pik, buffers=views)


class SharedPlane:
    """One process's handle onto the node-wide shared object segment."""

    def __init__(self, name: str, *, create: bool,
                 capacity: int = DEFAULT_CAPACITY,
                 max_objects: int = 8192,
                 threshold: int = DEFAULT_THRESHOLD):
        self.name = name
        self.threshold = threshold
        self.store = ShmObjectStore(name=name, capacity=capacity,
                                    max_objects=max_objects, create=create)
        self._lock = threading.Lock()
        self._pinned: set[bytes] = set()
        # Objects THIS process wrote, oldest-first with their total
        # payload size: the spill victim scan (an owner can only spill
        # what it owns — its pin is the one it may drop).
        self._written: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        # Producing job per written object (tenancy arena budgets):
        # bytes are CHARGED to the job whose task produced them, so
        # pressure spill can victimize the over-budget tenant's cold
        # objects first instead of whoever happens to be oldest.
        self._written_jobs: dict = {}
        self._owner = create
        # Set by install(): the worker whose memory store carries the
        # spill URLs for objects swapped out of this arena.
        self._worker = None

    # -- write side ------------------------------------------------------

    def maybe_put(self, object_id: ObjectID, value: Any,
                  timeout: float = 2.0) -> bool:
        """Serialize ``value`` into the segment if its payload crosses the
        threshold. Returns True iff the object is now readable from shm."""
        # Cheap pre-screen: obviously-small values skip the pickle-to-
        # measure step entirely (pickling every int/str task result just
        # to learn it's under the threshold dominated small-task runs).
        if value is None or isinstance(value, (bool, int, float)):
            return False
        if isinstance(value, (str, bytes, bytearray)) and \
                len(value) < self.threshold:
            return False
        oid = object_id.binary()
        if self.store.contains(oid):
            return True
        try:
            buffers: list = []
            pik = cloudpickle.dumps(value, protocol=5,
                                    buffer_callback=buffers.append)
            raws = [b.raw() for b in buffers]
        except Exception:
            return False  # unpicklable / non-contiguous buffer: heap path
        total_payload = len(pik) + sum(r.nbytes for r in raws)
        if total_payload < self.threshold:
            return False

        # Layout: magic | u32 npickle | u32 nbuffers |
        #         nbuffers * (u64 offset, u64 length) | pickle | buffers
        header_len = len(_MAGIC) + 8 + 16 * len(raws)
        pik_off = header_len
        offs = []
        cursor = _align(pik_off + len(pik))
        for r in raws:
            offs.append((cursor, r.nbytes))
            cursor = _align(cursor + r.nbytes)
        total = cursor

        deadline = time.monotonic() + timeout
        while True:
            off = self.store._lib.shm_obj_create(
                self.store._handle, oid, total)
            if off != 2**64 - 1:
                break
            # Arena full even after the C side evicted every unpinned
            # object: spill our own cold swapped entries to disk (URL
            # on the store entry, restore on get) so the create can
            # proceed, instead of looping on cross-process releases.
            if self._spill_for_space(total, exclude=oid) > 0:
                continue
            # Create-queue backpressure: eviction may need releases from
            # other processes; wait briefly and retry (the reference's
            # create-request-queue, `plasma/create_request_queue.h`).
            _BACKPRESSURE_WAITS.inc()
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

        view = self.store._view
        cur = off
        view[cur:cur + len(_MAGIC)] = _MAGIC
        cur += len(_MAGIC)
        view[cur:cur + 4] = len(pik).to_bytes(4, "little")
        view[cur + 4:cur + 8] = len(raws).to_bytes(4, "little")
        cur += 8
        for boff, blen in offs:
            view[cur:cur + 8] = boff.to_bytes(8, "little")
            view[cur + 8:cur + 16] = blen.to_bytes(8, "little")
            cur += 16
        view[off + pik_off:off + pik_off + len(pik)] = pik
        for (boff, blen), r in zip(offs, raws):
            if blen:
                view[off + boff:off + boff + blen] = r.cast("B")
        ok = bool(self.store._lib.shm_obj_seal(self.store._handle, oid))
        if ok:
            job = self._job_of_entry(object_id)
            with self._lock:
                self._written[oid] = total
                self._written.move_to_end(oid)
                if job:
                    self._written_jobs[oid] = job
        return ok

    def _job_of_entry(self, object_id: ObjectID) -> str:
        """Producing job of the object being published, read from the
        worker's store entry (the tags PR 6 put there)."""
        worker = self._worker
        if worker is None:
            return ""
        store = getattr(worker, "memory_store", None)
        if store is None or not hasattr(store, "entry_job"):
            return ""
        try:
            return store.entry_job(object_id)
        except Exception:
            return ""

    def job_arena_bytes(self) -> dict:
        """Arena bytes charged per producing job over this process's
        written objects ("" = untagged) — job_summary's ``arena_bytes``
        and the budget check's usage side."""
        out: dict = {}
        with self._lock:
            for oid, size in self._written.items():
                job = self._written_jobs.get(oid, "")
                out[job] = out.get(job, 0) + size
        return out

    # -- read side -------------------------------------------------------

    def get(self, object_id: ObjectID) -> Tuple[bool, Any]:
        """(found, value). Arrays in the value are zero-copy views over
        the segment; the object stays pinned until `release`."""
        oid = object_id.binary()
        buf = self.store.get_bytes(oid)  # pins on success
        if buf is None:
            return False, None
        try:
            pik, offs = _parse_header(buf)
            if pik is None:
                self.store.release(oid)
                return False, None
            base = self.store._view
            # Offsets are relative to the object payload; rebase onto the
            # process-wide mapping so views outlive `buf`.
            obj_off = self._payload_offset(oid)
            # Read-only views: sealed objects are immutable; a writable
            # reconstructed array would let readers corrupt shared memory.
            views = [base[obj_off + boff:obj_off + boff + blen]
                     .toreadonly() for boff, blen in offs]
            value = pickle.loads(pik, buffers=views)
        except Exception:
            self.store.release(oid)
            raise
        with self._lock:
            if oid in self._pinned:
                # Already pinned by an earlier get: drop the extra pin.
                self.store.release(oid)
            else:
                self._pinned.add(oid)
        return True, value

    def _payload_offset(self, oid: bytes) -> int:
        import ctypes

        size = ctypes.c_uint64()
        off = self.store._lib.shm_obj_get(self.store._handle, oid,
                                          ctypes.byref(size))
        if off == 2**64 - 1:
            raise KeyError("object vanished from shm store")
        self.store.release(oid)  # balance the extra pin from the lookup
        return off

    def payload_bytes(self, oid: bytes) -> Optional[bytes]:
        """Self-contained copy of the sealed RTS1 payload (the spill
        write source; `decode_payload` reverses it)."""
        buf = self.store.get_bytes(oid)  # pins on success
        if buf is None:
            return None
        try:
            return bytes(buf)
        finally:
            self.store.release(oid)

    def contains(self, object_id: ObjectID) -> bool:
        return self.store.contains(object_id.binary())

    def release(self, object_id: ObjectID) -> None:
        oid = object_id.binary()
        with self._lock:
            if oid not in self._pinned:
                return
            self._pinned.discard(oid)
        self.store.release(oid)

    def evict_object(self, object_id: ObjectID) -> None:
        """Owner-side free: drop our pin and reclaim the arena block if
        no other process still pins it (driver refcount hit zero — the
        head's free fan-out). A pinned object is left to the C store's
        LRU eviction once its readers release."""
        oid = object_id.binary()
        self.release(object_id)
        try:
            self.store.delete(oid)
        except Exception:
            pass
        with self._lock:
            self._written.pop(oid, None)
            self._written_jobs.pop(oid, None)

    # -- spill-to-disk under arena pressure ------------------------------

    def _spill_for_space(self, needed: int, exclude: bytes = b"") -> int:
        """Spill this owner's cold swapped objects until ``needed``
        arena bytes are reclaimed (or no eligible victim remains).
        Eligible = written by us, pinned ONLY by us (shm refcount 1 —
        no other process holds a zero-copy view), and the memory-store
        entry's sole-holder check passes (`spill_shm_entry`). Returns
        bytes reclaimed."""
        from ray_tpu._private.config import ray_config

        if not ray_config.shm_spill_enabled:
            return 0
        worker = self._worker
        if worker is None:
            return 0
        store = worker.memory_store
        if store.spill_manager is None:
            return 0
        freed = 0
        with self._lock:
            candidates = [ob for ob in self._written if ob != exclude]
            job_of = dict(self._written_jobs)
        # Tenancy arena budgets: victimize the OVER-BUDGET jobs' cold
        # objects first (cold-first within each tier — `_written` is
        # oldest-first), so one tenant's oversized working set spills
        # ITSELF before it can evict another tenant's bytes.
        over = tenancy.over_budget_jobs(self.job_arena_bytes())
        if over:
            candidates = tenancy.order_spill_victims(
                candidates, lambda ob: job_of.get(ob, ""), over)
        for ob in candidates:
            if freed >= needed:
                break
            rc = self.store.refcount(ob)
            if rc < 0:
                # Evicted/deleted behind our back: drop the stale entry.
                with self._lock:
                    self._written.pop(ob, None)
                continue
            if rc != 1:
                continue  # another process's view pins it, or nobody
                #           pins it (C eviction owns refcount-0 objects)
            with self._lock:
                if ob not in self._pinned:
                    continue  # the one pin is not ours to drop
            if store.spill_shm_entry(ObjectID(ob), self) is None:
                continue
            size = self.store.object_size(ob) or 0
            self.release(ObjectID(ob))
            if self.store.delete(ob):
                freed += size
                _SHM_SPILLS.inc()
                # Spilled bytes are charged to the producing job: the
                # hog sees its own pressure in job_summary/metrics.
                tenancy.arena_spill_counter(
                    job_of.get(ob, "")).inc(size)
            with self._lock:
                self._written.pop(ob, None)
                self._written_jobs.pop(ob, None)
        return freed

    def stats(self) -> dict:
        return self.store.stats()

    # -- lifecycle -------------------------------------------------------

    def install(self, worker) -> None:
        """Attach this plane to a Worker: large puts/outputs get shared,
        and MemoryStore entry GC releases shm pins."""
        worker.shm_plane = self
        self._worker = worker
        store = worker.memory_store
        plane = self

        orig_remove = store.remove_local_ref

        def remove_local_ref(object_id):
            entry = store._entries.get(object_id)
            last = entry is not None and entry.local_refs <= 1
            zero = orig_remove(object_id)
            if last and object_id not in store._entries:
                plane.release(object_id)
            return zero  # the became-zero signal drives cluster release

        store.remove_local_ref = remove_local_ref

    def close(self):
        with self._lock:
            pinned, self._pinned = list(self._pinned), set()
        for oid in pinned:
            try:
                self.store.release(oid)
            except Exception:
                pass
        self.store.close()

    def destroy(self, unmap: bool = True):
        """Tear the segment down. ``unmap=False`` unlinks the name but
        leaves the mapping intact: in-flight readers on other threads
        (driver fetch loops during cluster shutdown) would otherwise
        fault on unmapped memory; the pages free at process exit."""
        if unmap:
            self.close()
        else:
            with self._lock:
                pinned, self._pinned = list(self._pinned), set()
            for oid in pinned:
                try:
                    self.store.release(oid)
                except Exception:
                    pass
            self.store.stop_transfer_server()
        try:
            self.store._lib.shm_store_destroy(self.name.encode())
        except Exception:
            pass


def share_value(worker, object_id: ObjectID, value: Any) -> bool:
    """Publish a worker-local value into the node's shared plane (no-op
    without a plane or for small values)."""
    plane: Optional[SharedPlane] = getattr(worker, "shm_plane", None)
    if plane is None or value is None:
        return False
    try:
        return plane.maybe_put(object_id, value)
    except Exception:
        return False


def publish_task_output(worker, object_id: ObjectID, value: Any) -> bool:
    """Publish a task output into the node segment AND swap the local
    heap entry to the zero-copy shm view: a large output then lives
    ONCE, in the (budgeted, spillable) arena, instead of heap+arena —
    the reference's plasma promotion of worker return values."""
    plane: Optional[SharedPlane] = getattr(worker, "shm_plane", None)
    if plane is None or value is None:
        return False
    try:
        if not plane.maybe_put(object_id, value):
            return False
        found, view_value = plane.get(object_id)  # pins on success
        if not found:
            return True  # raced an eviction: the heap copy stands
        if not worker.memory_store.swap_to_shm(object_id, view_value):
            # Entry gone or errored (freed/failed concurrently): nothing
            # will ever release this pin, so drop it now. (An already-
            # swapped entry reports success and keeps the pin, which
            # get()'s dedup made singular.)
            plane.release(object_id)
        return True
    except Exception:
        return False
