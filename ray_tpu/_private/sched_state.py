"""Scheduler-state cores: the dep-park table and lock-partitioned maps.

Two building blocks for O(small) per-task control-plane cost at
1M-queued-task / 10k-actor scale:

- :class:`DepTable` — the dependency-parked work ledger, extracted from
  ``LocalBackend``'s inline dict pair into a pure decision core (same
  discipline as ``actor_gate.py`` / ``tenancy.py``: locks and counters,
  no RPC, no threads, no product imports) so the bounded model checker
  (``tools/raymc`` ``dep_sweep`` scenario) can prove the
  exactly-once-handoff invariant between the ready path and a death
  sweep over every interleaving at small scope — ROADMAP FT gap (d).
  Reference role: ``dependency_manager.h`` queued-task bookkeeping.

- :class:`ShardedTable` — a dict partitioned over independently-locked
  shards, the lock-partitioned form of the head's hot scheduling
  tables (in-flight dispatches, object directory, lineage). Concurrent
  submit batches and node object reports touch different shards and
  stop serializing on one head lock; per-key operations stay atomic
  under their shard's lock. Reference role: the GCS tables are
  per-component services with independent locks, not one mutex.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks


def round_up_pow2(n: int) -> int:
    """Smallest power of two >= max(1, n) — shard counts must be
    powers of two so ``hash(key) & mask`` partitions evenly."""
    out = 1
    while out < max(1, int(n)):
        out <<= 1
    return out


def stable_shard_of(key: bytes, n_shards: int) -> int:
    """Process-stable key -> shard partition (crc32, NOT the builtin
    ``hash`` — that one is salted per process). This is the map the
    multi-process head (``_private/head_shards.py``) routes by: the
    same key must land on the same shard across coordinator restarts
    so a failed-over head finds durable rows where its predecessor
    left them. In-process ``ShardedTable`` partitioning keeps the
    cheaper salted hash — its shards share one address space and never
    outlive the process."""
    if n_shards <= 1:
        return 0
    if not isinstance(key, (bytes, bytearray)):
        key = repr(key).encode()
    import zlib

    return zlib.crc32(key) % n_shards


def milli_add(acc: Dict[str, int], milli: Dict[str, int]) -> None:
    """Accumulate a milli-resource request into ``acc`` in place."""
    for k, v in milli.items():
        acc[k] = acc.get(k, 0) + v


def milli_sub(acc: Dict[str, int], milli: Dict[str, int]) -> None:
    """Subtract a milli-resource request from ``acc`` in place,
    pruning keys at (or clamping below) zero."""
    for k, v in milli.items():
        left = acc.get(k, 0) - v
        if left > 0:
            acc[k] = left
        else:
            acc.pop(k, None)


class DepTable:
    """Dependency-parked queued work with exactly-once handoff.

    A parked item is CLAIMED exactly once — either by the ready path
    (its last unresolved dependency arrived; :meth:`dep_ready` returns
    it) or by a sweep (its actor died, the node is shutting down;
    :meth:`sweep` returns it) — never both, never neither. The loser of
    a ready/sweep race observes nothing. Claim state is the presence of
    the item's remaining-count row: both paths delete it atomically
    under the one lock, and per-dep list entries whose row is gone are
    stale and skipped (and purged by the next sweep), so an item parked
    under several dependencies is still handed out once.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # dep key -> [(item key, item)] still parked under that dep.
        self._by_dep: Dict[Any, List[Tuple[bytes, Any]]] = {}
        # item key -> remaining unresolved deps; presence IS the claim.
        self._counts: Dict[bytes, int] = {}

    def park(self, key: bytes, item: Any, deps: List[Any]) -> None:
        """Park ``item`` until every dep in ``deps`` has fired (caller
        guarantees ``deps`` is non-empty and de-duplicated)."""
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.park", "call", self,
                                   (key, item, deps))
        with self._lock:
            self._counts[key] = len(deps)
            for dep in deps:
                self._by_dep.setdefault(dep, []).append((key, item))
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.park", "ret", self, None)

    def dep_ready(self, dep: Any) -> List[Any]:
        """One dependency resolved: returns the items this completes
        (claimed — the caller now owns dispatching them)."""
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.ready", "call", self, dep)
        sanitize_hooks.sched_point("sched.dep_ready")
        out: List[Any] = []
        with self._lock:
            for key, item in self._by_dep.pop(dep, ()):
                left = self._counts.get(key)
                if left is None:
                    continue  # claimed by a sweep while parked
                if left > 1:
                    self._counts[key] = left - 1
                else:
                    del self._counts[key]
                    out.append(item)
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.ready", "ret", self, out)
        return out

    def sweep(self, match: Callable[[Any], bool]) -> List[Any]:
        """Claim and return every still-parked item ``match`` selects
        (death sweep / shutdown). Purges the claimed items' entries
        from every per-dep list — a dep that never fires must not pin
        swept items forever."""
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.sweep", "call", self, None)
        sanitize_hooks.sched_point("sched.dep_sweep")
        out: List[Any] = []
        with self._lock:
            claimed: set = set()
            for dep in list(self._by_dep):
                kept = []
                for key, item in self._by_dep[dep]:
                    if key in claimed:
                        continue  # claimed via an earlier dep's list
                    if key not in self._counts:
                        continue  # stale: already handed out — purge
                    if match(item):
                        del self._counts[key]
                        claimed.add(key)
                        out.append(item)
                    else:
                        kept.append((key, item))
                if kept:
                    self._by_dep[dep] = kept
                else:
                    del self._by_dep[dep]
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.dep.sweep", "ret", self, out)
        return out

    def waiting_count(self) -> int:
        """Items parked and unclaimed (the ``waiting_for_deps`` gauge)."""
        with self._lock:
            return len(self._counts)

    def parked_entries(self) -> int:
        """Total per-dep list entries (leak canary for tests: stale
        entries of claimed items must not accumulate unboundedly)."""
        with self._lock:
            return sum(len(v) for v in self._by_dep.values())


class ShardedTable:
    """A mapping partitioned over independently-locked dict shards.

    Per-key operations (get/set/pop/contains) are atomic under the
    key's shard lock only, so operations on different shards run
    concurrently. Iteration (:meth:`items` / :meth:`values`) snapshots
    shard-by-shard — consistent per shard, not across shards — which is
    the contract the head's sweep/scan users already tolerate (a report
    racing a death sweep could always land wholly before or after it).
    Callers holding an UNRELATED outer lock may call in (shard locks
    are leaf locks: nothing is acquired while one is held).
    """

    __slots__ = ("_shards", "_locks", "_mask")

    def __init__(self, shards: int = 16):
        n = round_up_pow2(shards)
        self._mask = n - 1
        self._shards: List[dict] = [{} for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]

    def _ix(self, key) -> int:
        return hash(key) & self._mask

    # Per-key ops carry rayspec taps (spec.table.*): the recorded
    # concurrent history must refine ONE flat dict — the spec the
    # lock-partitioned form exists to preserve. Iteration stays
    # untapped: its contract is explicitly weaker (per-shard, not
    # cross-shard, consistency) and outside the refinement map.

    def get(self, key, default=None):
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.get", "call", self, key)
        i = self._ix(key)
        with self._locks[i]:
            out = self._shards[i].get(key, default)
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.get", "ret", self, (key, out))
        return out

    def __contains__(self, key) -> bool:
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.contains", "call", self, key)
        i = self._ix(key)
        with self._locks[i]:
            out = key in self._shards[i]
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.contains", "ret", self,
                                   (key, out))
        return out

    def __setitem__(self, key, value) -> None:
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.set", "call", self,
                                   (key, value))
        i = self._ix(key)
        with self._locks[i]:
            self._shards[i][key] = value
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.set", "ret", self, (key, None))

    def __getitem__(self, key):
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.get", "call", self, key)
        i = self._ix(key)
        with self._locks[i]:
            out = self._shards[i][key]
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.get", "ret", self, (key, out))
        return out

    def pop(self, key, default=None):
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.pop", "call", self, key)
        i = self._ix(key)
        with self._locks[i]:
            out = self._shards[i].pop(key, default)
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.pop", "ret", self, (key, out))
        return out

    def setdefault(self, key, default):
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.setdefault", "call", self,
                                   (key, default))
        i = self._ix(key)
        with self._locks[i]:
            out = self._shards[i].setdefault(key, default)
        if sanitize_hooks.spec_taps_active:
            sanitize_hooks.spec_op("spec.table.setdefault", "ret", self,
                                   (key, out))
        return out

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def items(self) -> List[tuple]:
        out: List[tuple] = []
        for i, shard in enumerate(self._shards):
            with self._locks[i]:
                out.extend(shard.items())
        return out

    def values(self) -> List[Any]:
        return [v for _, v in self.items()]

    def keys(self) -> List[Any]:
        return [k for k, _ in self.items()]


class PendingCounter:
    """Incremental queued-demand accounting under its own small lock
    (split off the backend's dep/bookkeeping lock so the submit fast
    path's add/remove never contends with dep parking): total queued
    count plus summed milli-resource demand — the backlog signal
    (reference: raylet backlog reporting in lease requests)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._milli: Dict[str, int] = {}
        self._count = 0

    def add(self, milli: Dict[str, int]) -> None:
        with self._lock:
            self._count += 1
            milli_add(self._milli, milli)

    def remove(self, milli: Dict[str, int]) -> None:
        with self._lock:
            self._count = max(0, self._count - 1)
            milli_sub(self._milli, milli)

    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def count_approx(self) -> int:
        """Lock-free read for racy fast-path gates (a stale value only
        routes work to the always-correct slow path)."""
        return self._count

    def demand_milli(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._milli)


def class_is_async(cls) -> Optional[bool]:
    """Cached "does this actor class define any coroutine method"
    probe: the inspect.getmembers scan costs ~100µs per call, which at
    10k-actor creation rates was a visible per-creation tax. Bounded
    cache (dynamically minted classes must not pin forever); None when
    ``cls`` is not a class."""
    import inspect

    if not inspect.isclass(cls):
        return None
    cached = _ASYNC_CACHE.get(cls)
    if cached is None:
        cached = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(
                cls, predicate=inspect.isfunction))
        with _ASYNC_CACHE_LOCK:
            if len(_ASYNC_CACHE) >= 4096:
                _ASYNC_CACHE.clear()
            _ASYNC_CACHE[cls] = cached
    return cached


_ASYNC_CACHE: Dict[type, bool] = {}
_ASYNC_CACHE_LOCK = threading.Lock()
