"""Per-node physical stats sampling.

Reference: the per-node dashboard agent's reporter module
(`dashboard/agent.py` hosting `reporter_agent.py` — psutil stats pushed
to the head over `reporter.proto`). This runtime is single-language and
the node process already maintains a push channel to the head (the
resource-report loop), so the agent's reporting role rides that channel
instead of a separate process: `sample_node_stats()` piggybacks on every
resource report, and the head keeps the latest sample per node for the
state API / dashboard.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict


def sample_node_stats() -> Dict[str, Any]:
    try:
        import psutil
    except ImportError:  # pragma: no cover
        return {"ts": time.time()}

    try:
        vm = psutil.virtual_memory()
        disk = psutil.disk_usage("/")
        la = os.getloadavg()
        return {
            "ts": time.time(),
            "cpu_percent": psutil.cpu_percent(interval=None),
            "cpu_count": psutil.cpu_count(),
            "load_avg": la,
            "mem_total": vm.total,
            "mem_available": vm.available,
            "mem_percent": vm.percent,
            "disk_total": disk.total,
            "disk_free": disk.free,
            "disk_percent": disk.percent,
            "pid_count": len(psutil.pids()),
        }
    except Exception:  # pragma: no cover — never break the report loop
        return {"ts": time.time()}
