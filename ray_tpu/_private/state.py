"""Global control state: the in-process GCS.

Role-equivalent to the reference GCS server's managers
(``src/ray/gcs/gcs_server/``): named-actor registry (GcsActorManager's
by-name index), internal KV (``gcs_kv_manager.h``), node table, and
placement-group table. In cluster mode this state lives in the head
process and is accessed over the control-plane RPC.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID


class GlobalState:
    def __init__(self, worker):
        from ray_tpu._private.gcs_storage import make_store_client

        self._worker = worker
        self._lock = threading.Lock()
        # (namespace, name) -> actor handle info
        self._named_actors: Dict[tuple, Any] = {}
        self._kv: Dict[tuple, bytes] = {}
        self._placement_groups: Dict[PlacementGroupID, Any] = {}
        # Pluggable table storage (reference: gcs_table_storage.h over
        # store_client/): in-memory by default; with a configured
        # gcs_storage_path the KV table is durable — a restarted head
        # reloads it (the reference's Redis-backed GCS FT story).
        self._store = make_store_client()
        for key, value in self._store.get_all("kv"):
            ns, _, k = key.partition(b"\x00")
            self._kv[(ns, k)] = value
        # Named-actor and placement-group tables are durable too
        # (reference: GcsActorManager / GcsPlacementGroupManager persist
        # through gcs_table_storage) — a restarted head recovers both.
        import cloudpickle

        for key, value in self._store.get_all("named_actors"):
            ns, _, name = key.partition(b"\x00")
            try:
                self._named_actors[(ns.decode(), name.decode())] = \
                    cloudpickle.loads(value)
            except Exception:
                pass
        for key, value in self._store.get_all("pgs"):
            try:
                pg = _pg_from_blob(value)
                self._placement_groups[pg.id] = pg
            except Exception:
                pass

    # -- named actors ----------------------------------------------------

    @staticmethod
    def _named_store_key(key: tuple) -> bytes:
        return key[0].encode() + b"\x00" + key[1].encode()

    def register_named_actor(self, name: str, namespace: Optional[str],
                             handle) -> None:
        key = (namespace or self._worker.namespace, name)
        with self._lock:
            if key in self._named_actors:
                raise ValueError(
                    f"Actor name {name!r} already taken in namespace {key[0]!r}"
                )
            self._named_actors[key] = handle
            try:
                import cloudpickle

                self._store.put("named_actors",
                                self._named_store_key(key),
                                cloudpickle.dumps(handle))
            except Exception:
                pass  # unpicklable handle: stays memory-only

    def get_named_actor(self, name: str, namespace: Optional[str]):
        key = (namespace or self._worker.namespace, name)
        with self._lock:
            handle = self._named_actors.get(key)
        if handle is None:
            raise ValueError(f"Failed to look up actor {name!r}")
        return handle

    def list_named_actors(self, all_namespaces: bool = False):
        with self._lock:
            if all_namespaces:
                return [
                    {"name": n, "namespace": ns} for (ns, n) in self._named_actors
                ]
            return [
                n for (ns, n) in self._named_actors
                if ns == self._worker.namespace
            ]

    def remove_named_actor_by_id(self, actor_id: ActorID) -> None:
        with self._lock:
            for key, handle in list(self._named_actors.items()):
                if handle._actor_id == actor_id:
                    del self._named_actors[key]
                    self._store.delete("named_actors",
                                       self._named_store_key(key))

    # -- multi-process head fold ----------------------------------------

    def head_shard_state(self) -> dict:
        """Whole-table control-plane view folded across every head
        shard process (the timeline/state-merge path for a sharded
        head): row counts per durable table plus per-shard stats.
        Empty dict when the head runs single-process
        (``head_shards=1``)."""
        head = getattr(self._worker.backend, "head", None)
        router = getattr(head, "shard_router", None) \
            if head is not None else None
        if router is None:
            return {}
        from ray_tpu._private.head_shards import DURABLE_TABLES

        return {
            "shards": router.n_shards,
            "restarts": router.restarts,
            "tables": {t: len(router.fold_items(t))
                       for t in DURABLE_TABLES},
            "per_shard": router.stats(),
        }

    # -- internal KV (reference: gcs_kv_manager.h) -----------------------

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: Optional[bytes] = None) -> bool:
        k = (namespace or b"", key)
        with self._lock:
            if not overwrite and k in self._kv:
                return False
            self._kv[k] = value
            self._store.put("kv", k[0] + b"\x00" + k[1], value)
            return True

    def kv_get(self, key: bytes, namespace: Optional[bytes] = None) -> Optional[bytes]:
        with self._lock:
            return self._kv.get((namespace or b"", key))

    def kv_del(self, key: bytes, namespace: Optional[bytes] = None) -> None:
        k = (namespace or b"", key)
        with self._lock:
            self._kv.pop(k, None)
            self._store.delete("kv", k[0] + b"\x00" + k[1])

    def kv_keys(self, prefix: bytes, namespace: Optional[bytes] = None) -> list:
        ns = namespace or b""
        with self._lock:
            return [k for (n, k) in self._kv if n == ns and k.startswith(prefix)]

    # -- placement groups ------------------------------------------------

    def register_placement_group(self, pg) -> None:
        with self._lock:
            self._placement_groups[pg.id] = pg
            try:
                self._store.put("pgs", pg.id.binary(), _pg_to_blob(pg))
            except Exception:
                pass

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            self._placement_groups.pop(pg_id, None)
            self._store.delete("pgs", pg_id.binary())

    def placement_group_table(self) -> dict:
        with self._lock:
            return dict(self._placement_groups)

    # -- storage lifecycle ----------------------------------------------

    def flush_storage(self) -> None:
        """Force deferred durable writes to disk (group-commit drain).
        Called at graceful teardown boundaries — worker shutdown, head
        failover handoff — so a successor process's fresh store
        connection sees everything this one accepted."""
        try:
            self._store.flush()
        except Exception:
            pass

    def close_storage(self) -> None:
        try:
            self._store.close()
        except Exception:
            pass

    def crash_storage(self) -> None:
        """Hard-crash teardown (crash-mode head failover): the store
        connection drops WITHOUT flushing — at most the open
        group-commit window (``gcs_commit_interval_s``) of accepted-
        but-unflushed writes is lost, and none of them can resurrect."""
        try:
            self._store.crash()
        except Exception:
            pass

    # -- cluster introspection -------------------------------------------

    def nodes(self) -> list:
        b = self._worker.backend
        head = getattr(b, "head", None)
        if head is not None and hasattr(head, "_get_nodes"):
            return head._get_nodes()  # cluster mode: full node table
        from ray_tpu._private.node_stats import sample_node_stats

        return [
            {
                "NodeID": b.node_id.hex(),
                "Alive": True,
                "Resources": b.resources.total,
                "Available": b.resources.available,
                "Labels": getattr(b, "labels", {}),
                "Stats": sample_node_stats(),
            }
        ]

    def cluster_resources(self) -> Dict[str, float]:
        return self._worker.backend.resources.total

    def available_resources(self) -> Dict[str, float]:
        return self._worker.backend.resources.available


def _pg_to_blob(pg) -> bytes:
    """Placement groups persist as PLAIN data (the handle's __reduce__
    resolves through the live registry, which doesn't exist while a
    restarted head is still loading its tables)."""
    import pickle

    return pickle.dumps({
        "id": pg.id.binary(),
        "bundles": pg.bundle_specs,
        "strategy": pg.strategy,
        "name": pg.name,
        "bundle_nodes": getattr(pg, "bundle_nodes", None),
    })


def _pg_from_blob(blob: bytes):
    import pickle

    from ray_tpu.util.placement_group import PlacementGroup

    d = pickle.loads(blob)
    pg = PlacementGroup(PlacementGroupID(d["id"]), d["bundles"],
                        d["strategy"], d["name"])
    if d.get("bundle_nodes") is not None:
        pg.bundle_nodes = d["bundle_nodes"]
        pg._ready.set()
    else:
        # Persisted at registration but the head died before the
        # reservation committed: surface a clean failure instead of a
        # phantom-ready group whose bundles were never placed.
        pg._failed = ("placement-group reservation was in flight when "
                      "the head restarted; re-create the group")
        pg._ready.set()
    return pg
