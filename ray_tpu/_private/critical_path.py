"""Critical-path attribution: where did a request's wall time go?

The trace plane (PR 3) can show *that* a request crossed the proxy, a
router, a replica, and an LLM engine; the SLO plane (PR 6/15) can show
*that* a route is slow. Neither can answer the operator's actual
question — *which stage* made THIS request slow — and that attribution
is the measured input every adaptive control-loop decision (ROADMAP
item 4) needs.

This module is the pure core. Hot paths call :func:`record_stage` —
ONE scalar-tuple append to a bounded deque, nothing else — at every
seam a request crosses (proxy dispatch, router assign, replica-direct
acquire, replica execute, LLM admit/kv-lookup/prefill/first-token/
decode, scheduler queue, object-plane pull/spill/restore). Everything
downstream of that append (trace accumulation, histogram folds,
exemplar upkeep, the flight ring, the ship queue) happens in
:func:`flush`, driven by a process-lifetime folder thread at ~100 ms
cadence and synchronously by every reader. The deferral is the whole
performance story: on a serial request path every instruction between
"replica produced the result" and "client read the response" is paid
at GIL-scheduling granularity, so 20 µs of inline folding measured as
~70 µs of added latency — while an append costs ~0.15 µs and the fold
runs when the loop would otherwise be idle. The proxy's request
envelope calls :func:`finish_request` once per request, which (at
fold time):

- attributes the request's wall time to its recorded stages (the
  remainder is folded as the ``unattributed`` stage, so the vector
  always sums to the measured total),
- folds each stage duration into the
  ``request_stage_seconds{route,stage}`` fast-path distribution —
  exported as ``ray_tpu_request_stage_seconds_p50/_p99`` per
  (route, stage) by ``runtime_metrics``, the per-route *attribution
  vector*,
- pins an exemplar trace-id to the slowest observation per histogram
  bucket (the Prometheus-exemplar idea, JSON-shaped), and
- retains a bounded waterfall for ``/api/slow_requests`` and the CLI
  ``ray_tpu slow``.

Stage records born on worker nodes ride the existing obs shipper
(``drain_records`` → ``obs_report(stages=...)`` → :func:`ingest`), so
the head folds cluster-wide attribution — replica/engine stages land
seconds after the proxy already finished the request, which is why
late arrivals for a finished trace fold immediately against the
route the finish recorded.

Layering: imports only peer ``_private`` modules (perf_stats,
flight_recorder); never serve.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import flight_recorder, perf_stats
from ray_tpu._private.config import ray_config

ENABLED = True


def _on() -> bool:
    return ENABLED and ray_config.stage_spans_enabled

# Bounded process-global state. Aliasing contract matches perf_stats:
# hot paths reference the module, tests snapshot/restore IN PLACE.
MAX_TRACES = 2048          # in-flight trace accumulators
MAX_STAGES_PER_TRACE = 64  # a runaway decode loop can't grow one trace
MAX_FINISHED = 256         # retained waterfalls for slow_requests
MAX_PENDING = 8192         # node-side records awaiting shipping

STAGE_METRIC = "request_stage_seconds"

# Attribution floor: spans shorter than this are noise at SLO scale
# (they cannot be dominant, and the tiling contract charges their time
# to ``unattributed`` regardless) — dropping them at the record site
# is the single biggest term in the recorder's fast-route overhead.
MIN_SPAN_S = 5e-5

# A record is the tuple (t, trace_id, stage, dur_s, route); the dict
# shape only exists at the edges (the obs-ship wire format, snapshots).
# A finish marker is the 6-tuple (t, trace_id, status, total_s, route,
# None) — length is the dispatch tag.
_T, _TRACE, _STAGE, _DUR, _ROUTE = range(5)

# Raw hot-path appends awaiting a fold. Sized for several fold periods
# at full serve throughput; sustained overflow drops oldest (bounded
# memory beats bounded truth for a diagnostics plane).
MAX_RAW = 65536
_raw: "deque[tuple]" = deque(maxlen=MAX_RAW)

_FOLD_PERIOD_S = 0.1
_folder_started = False
_folder_lock = threading.Lock()

_lock = threading.Lock()
# trace_id -> [stages[(stage, dur_s)], route, t0]
_traces: "OrderedDict[str, list]" = OrderedDict()
# finished waterfalls, oldest-first ("stages" holds (stage, dur) pairs)
_finished: "deque[dict]" = deque(maxlen=MAX_FINISHED)
# trace_id -> route for finished traces: late-arriving node records
# (shipped after the proxy closed the request) still fold.
_finished_routes: "OrderedDict[str, str]" = OrderedDict()
# record tuples awaiting the obs shipper. Only processes that actually
# ship (a NodeObsShipper exists) pay the append: the head folds its own
# records in place and would otherwise queue 8192 tuples for nobody.
SHIPPING = False
_pending: "deque[tuple]" = deque(maxlen=MAX_PENDING)
# (route, stage) -> {bucket_index: (dur_s, trace_id)} — slowest
# observation per histogram bucket.
_exemplars: Dict[Tuple[str, str], Dict[int, Tuple[float, str]]] = {}
# (route, stage) -> interned Dist. perf_stats mutates interned stats in
# place (never replaces them), so caching skips the sorted-tuple key
# build + registry probe on every finish_request fold.
_dist_cache: Dict[Tuple[str, str], perf_stats.Dist] = {}


def set_enabled(on: bool) -> None:
    """A/B kill switch (``perf_bench.py --ab-observability`` flips it
    to prove the stage-span tax on the serve keep-alive path)."""
    global ENABLED
    ENABLED = bool(on)


def enabled() -> bool:
    """Public gate for call sites whose *argument computation* has a
    cost (ambient trace lookup, task-spec trace extraction) — skip it
    entirely when the recorder is off."""
    return _on()


def set_shipping(on: bool) -> None:
    """Mark this process as one whose records are drained by an obs
    shipper (worker nodes). Off — the default, and the head's state —
    ``record_stage`` skips the pending queue entirely."""
    global SHIPPING
    SHIPPING = bool(on)


# Lazily-bound (circular-import-safe) collaborators of
# ambient_trace_id: resolved once, not per request — the sys.modules
# probes of a per-call import are measurable on the serve fast path.
_ambient_fns: Optional[tuple] = None


def ambient_trace_id() -> Optional[str]:
    """Trace id of the currently executing task (None outside one) —
    what in-task stage sites (replica execute, LLM engine, object
    plane) attribute their work to. Cheap: two dict lookups when a
    task context exists."""
    global _ambient_fns
    try:
        if _ambient_fns is None:
            from ray_tpu._private.task_spec import trace_id_of
            from ray_tpu._private.worker import global_worker_or_none
            _ambient_fns = (trace_id_of, global_worker_or_none)
        trace_id_of, global_worker_or_none = _ambient_fns

        w = global_worker_or_none()
        if w is None:
            return None
        ctx = w.task_context.current()
        if ctx is None:
            return None
        return trace_id_of(ctx["task_spec"])
    except Exception:
        return None


def _stage_dist(route: str, stage: str) -> perf_stats.Dist:
    key = (route, stage)
    d = _dist_cache.get(key)
    if d is None:
        d = perf_stats.dist(STAGE_METRIC,
                            {"route": route, "stage": stage},
                            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        _dist_cache[key] = d
    return d


# Exemplar floor: an exemplar exists so the operator can drill from a
# SLOW histogram bucket into one concrete trace. Observations below
# this land in buckets nobody ever drills into, and their upkeep
# (bisect + dict probe per stage per finish) would dominate the fold
# cost on fast routes.
_EXEMPLAR_MIN_S = 0.005


def _fold(route: str, stage: str, dur_s: float, trace_id: str) -> None:
    """One stage observation into the attribution vector + exemplars.
    Callers hold ``_lock`` (exemplar upkeep mutates a shared dict)."""
    _stage_dist(route, stage).record(dur_s)
    if dur_s < _EXEMPLAR_MIN_S:
        return
    idx = bisect.bisect_left(perf_stats.SERVE_LATENCY_BOUNDS, dur_s)
    bucket = _exemplars.setdefault((route, stage), {})
    cur = bucket.get(idx)
    if cur is None or dur_s > cur[0]:
        bucket[idx] = (dur_s, trace_id)


def record_stage(trace_id: Optional[str], stage: str, dur_s: float,
                 route: str = "") -> None:
    """Attribute ``dur_s`` seconds of ``stage`` work to ``trace_id``.

    Hot-path cost: one scalar-tuple append (GIL-atomic, no lock) —
    folding is deferred to :func:`flush`. Records without a trace id
    (object-plane work running outside any request) still reach the
    flight ring at fold time — they are real cluster activity the
    post-mortem wants — but never the attribution vectors.

    Spans under :data:`MIN_SPAN_S` are dropped at the door: a stage
    that took tens of microseconds can never be the answer to "which
    stage made this request slow", it folds into ``unattributed`` by
    the tiling contract anyway, and recording it costs exactly as much
    as recording a meaningful one — on a fast route the floor drops
    most of the per-request records."""
    if not _on() or dur_s < MIN_SPAN_S:
        return
    _raw.append((time.time(), trace_id or "", stage, float(dur_s),
                 route))
    if not _folder_started:
        _ensure_folder()


def finish_request(trace_id: Optional[str], route: str, status: str,
                   total_s: float) -> None:
    """Close a request: at fold time its stage vector (plus the
    unattributed remainder) lands in
    ``request_stage_seconds{route,stage}`` and the waterfall is
    retained. Called from the proxy's request envelope once per
    request — same one-append hot path as :func:`record_stage`."""
    if not _on() or not trace_id:
        return
    _raw.append((time.time(), trace_id, status, float(total_s), route,
                 None))
    if not _folder_started:
        _ensure_folder()


def _ensure_folder() -> None:
    """Start the process-lifetime folder thread (idempotent). It owns
    the fold cadence so no request ever pays for folding; readers
    still :func:`flush` synchronously for deterministic answers."""
    global _folder_started
    with _folder_lock:
        if _folder_started:
            return
        t = threading.Thread(target=_folder_loop, daemon=True,
                             name="critical-path-folder")
        t.start()
        _folder_started = True


def _folder_loop() -> None:
    while True:
        time.sleep(_FOLD_PERIOD_S)
        try:
            # Fold in small slices with a real sleep between them: one
            # monolithic fold of a period's backlog holds the GIL for
            # milliseconds at a stretch, and on a serial request path
            # that burst reads as added latency — the exact
            # amplification the deferral exists to remove. Sliced, the
            # folder's cost converges to its true CPU share.
            while flush(_FOLD_SLICE) == _FOLD_SLICE:
                time.sleep(0.002)
        except Exception:
            pass  # diagnostics must never take the process down


# Records folded per GIL slice in the folder thread. ~200 folds cost
# well under a millisecond; the 2ms yield between slices lets every
# in-flight request proceed before the next slice.
_FOLD_SLICE = 200


def flush(max_n: Optional[int] = None) -> int:
    """Drain raw hot-path appends into the folded state (traces, the
    flight ring, histograms, exemplars, retained waterfalls, the ship
    queue); returns the number of records folded. Idempotent and
    multi-thread safe: popleft is GIL-atomic so the folder thread and
    a concurrent reader each fold a record at most once. Readers call
    it unbounded for deterministic answers; the folder thread passes
    ``max_n`` to bound each GIL slice."""
    n = 0
    while max_n is None or n < max_n:
        try:
            rec = _raw.popleft()
        except IndexError:
            break
        if len(rec) == 5:
            _fold_span(rec)
        else:
            _fold_finish(rec)
        n += 1
    return n


def _fold_span(rec: tuple) -> None:
    trace_id = rec[_TRACE]
    flight_recorder.note_span(rec)
    if not trace_id:
        return
    if SHIPPING:
        _pending.append(rec)
    stage = rec[_STAGE]
    tr = _traces.get(trace_id)
    if tr is None:
        route_done = _finished_routes.get(trace_id)
        if route_done is not None:
            # Late arrival (node record shipped — or locally folded —
            # after the request closed): fold against the finished
            # route now.
            with _lock:
                _fold(route_done, stage, rec[_DUR], trace_id)
            return
        tr = _traces.setdefault(trace_id, [[], rec[_ROUTE], rec[_T]])
        if len(_traces) > MAX_TRACES:
            with _lock:
                while len(_traces) > MAX_TRACES:
                    _traces.popitem(last=False)
    if rec[_ROUTE] and not tr[1]:
        tr[1] = rec[_ROUTE]
    if len(tr[0]) < MAX_STAGES_PER_TRACE:
        tr[0].append((stage, rec[_DUR]))


def _fold_finish(rec: tuple) -> None:
    t, trace_id, status, total_s, route = rec[:5]
    with _lock:
        tr = _traces.pop(trace_id, None)
        stages = tr[0] if tr else []
        agg: Dict[str, float] = {}
        for stage, dur in stages:
            agg[stage] = agg.get(stage, 0.0) + dur
        for stage, dur in agg.items():
            _fold(route, stage, dur, trace_id)
        unattributed = max(0.0, total_s - sum(agg.values()))
        _fold(route, "unattributed", unattributed, trace_id)
        agg["unattributed"] = unattributed
        dominant = max(agg.items(), key=lambda kv: kv[1])[0]
        _finished.append({
            "trace_id": trace_id, "route": route, "status": status,
            "total_s": total_s, "dominant_stage": dominant,
            "unattributed_s": unattributed, "ts": t,
            "stages": stages,
        })
        _finished_routes[trace_id] = route
        while len(_finished_routes) > MAX_TRACES:
            _finished_routes.popitem(last=False)


def ingest(records: Optional[List[dict]]) -> None:
    """Head-side fold of node-shipped stage records (the
    ``obs_report(stages=...)`` path). Same accumulation as a local
    :func:`record_stage`, minus re-shipping and re-ringing — the
    origin node already ringed them."""
    if not _on() or not records:
        return
    for rec in records:
        try:
            trace_id = rec["trace_id"]
            stage = rec["stage"]
            dur_s = float(rec["dur_s"])
            route = rec.get("route") or ""
        except (KeyError, TypeError, ValueError):
            continue  # malformed entry must not poison the frame
        if not trace_id:
            continue
        tr = _traces.get(trace_id)
        if tr is None:
            route_done = _finished_routes.get(trace_id)
            if route_done is not None:
                with _lock:
                    _fold(route_done, stage, dur_s, trace_id)
                continue
            tr = _traces.setdefault(
                trace_id, [[], route, rec.get("t") or time.time()])
        if route and not tr[1]:
            tr[1] = route
        if len(tr[0]) < MAX_STAGES_PER_TRACE:
            tr[0].append((stage, dur_s))


def _wire(rec: tuple) -> dict:
    """Record tuple -> the obs-ship wire shape :func:`ingest` reads."""
    return {"trace_id": rec[_TRACE], "stage": rec[_STAGE],
            "dur_s": rec[_DUR], "route": rec[_ROUTE], "t": rec[_T]}


def drain_records(max_n: int = 1000) -> List[dict]:
    """Pop up to ``max_n`` pending records for the obs shipper (worker
    nodes), in wire (dict) shape. Popleft is GIL-atomic; an empty race
    just ends the drain."""
    flush()
    out: List[dict] = []
    while len(out) < max_n:
        try:
            out.append(_wire(_pending.popleft()))
        except IndexError:
            break
    return out


def requeue_records(records: List[dict]) -> None:
    """Put drained records back after a failed ship (bounded: the deque
    drops oldest if the head stays unreachable)."""
    _pending.extend(
        (r["t"], r["trace_id"], r["stage"], r["dur_s"], r["route"])
        for r in records)


def _waterfall(entry: dict) -> dict:
    """Presentation shape shared by the API, the CLI, and the flight
    recorder: stages plus each stage's share of the total. Retained
    entries hold (stage, dur) pairs; the dict shape is built here, at
    read time, not per request."""
    total = entry.get("total_s") or 0.0
    stages = []
    for stage, dur in entry.get("stages") or []:
        frac = (dur / total) if total > 0 else 0.0
        stages.append({"stage": stage, "dur_s": dur,
                       "frac": round(frac, 4)})
    out = dict(entry)
    out["stages"] = stages
    return out


def slow_requests(n: int = 10,
                  include_inflight: bool = False) -> List[dict]:
    """Top-``n`` slowest retained requests (waterfalls, dominant stage
    named). ``include_inflight`` adds still-open traces (their total is
    age-so-far) — what the flight recorder wants mid-incident."""
    flush()
    with _lock:
        items = [dict(e) for e in _finished]
        if include_inflight:
            now = time.time()
            for trace_id, tr in _traces.items():
                agg: Dict[str, float] = {}
                for stage, dur in tr[0]:
                    agg[stage] = agg.get(stage, 0.0) + dur
                age = max(0.0, now - tr[2])
                items.append({
                    "trace_id": trace_id, "route": tr[1],
                    "status": "in_flight", "total_s": age,
                    "dominant_stage": max(agg.items(),
                                          key=lambda kv: kv[1])[0]
                    if agg else "unattributed",
                    "unattributed_s": max(
                        0.0, age - sum(agg.values())),
                    "ts": tr[2], "in_flight": True,
                    "stages": list(tr[0]),
                })
    items.sort(key=lambda e: e.get("total_s") or 0.0, reverse=True)
    return [_waterfall(e) for e in items[:max(0, n)]]


def exemplars() -> List[dict]:
    """Exemplar trace-ids for the slowest observation in each
    (route, stage) histogram bucket — the jump-off from a p99 panel to
    the trace that caused it."""
    flush()
    bounds = perf_stats.SERVE_LATENCY_BOUNDS
    out: List[dict] = []
    with _lock:
        for (route, stage), buckets in _exemplars.items():
            for idx, (dur_s, trace_id) in buckets.items():
                le = bounds[idx] if idx < len(bounds) else float("inf")
                out.append({"route": route, "stage": stage,
                            "bucket_le": le, "dur_s": dur_s,
                            "trace_id": trace_id})
    out.sort(key=lambda e: (e["route"], e["stage"], e["dur_s"]))
    return out


def attribution_vectors() -> Dict[str, Dict[str, Dict[str, float]]]:
    """{route: {stage: {p50, p99, count, sum}}} read straight from the
    fast-path dists — the JSON twin of the Prometheus exposition, used
    by ``/api/slow_requests`` and the CLI summary header."""
    flush()
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for name, tags, stat in perf_stats.stats_items():
        if name != STAGE_METRIC or not isinstance(stat, perf_stats.Dist):
            continue
        if stat.total == 0:
            continue  # interned-but-reset series: nothing to report
        tagd = dict(tags)
        route = tagd.get("route", "")
        stage = tagd.get("stage", "")
        out.setdefault(route, {})[stage] = {
            "p50": stat.quantile(0.5), "p99": stat.quantile(0.99),
            "count": stat.total, "sum": stat.sum}
    return out


def stage_spans_for_trace(trace_id: str) -> List[dict]:
    """The recorded stages for one trace (open or finished) — what
    ``export_spans`` merges into the OTLP view as synthetic child
    spans so a trace's stage anatomy rides the same trace id."""
    flush()
    with _lock:
        tr = _traces.get(trace_id)
        if tr is not None:
            return [{"stage": s, "dur_s": d} for s, d in tr[0]]
        for entry in _finished:
            if entry["trace_id"] == trace_id:
                return [{"stage": s, "dur_s": d}
                        for s, d in entry["stages"]]
    return []


def finished_waterfalls() -> List[dict]:
    flush()
    with _lock:
        out = []
        for e in _finished:
            e = dict(e)
            e["stages"] = [{"stage": s, "dur_s": d}
                           for s, d in e["stages"]]
            out.append(e)
        return out


# -- test isolation -----------------------------------------------------------


def snapshot_state() -> dict:
    """Plain-data snapshot of this module's process-global state; with
    :func:`restore_state` (both IN PLACE — hot paths alias the module
    globals) this is the conftest-baseline API that keeps one test's
    stage recordings out of the next."""
    flush()
    with _lock:
        return {
            "enabled": ENABLED,
            "shipping": SHIPPING,
            "traces": {k: [list(v[0]), v[1], v[2]]
                       for k, v in _traces.items()},
            "finished": [dict(e) for e in _finished],
            "finished_routes": dict(_finished_routes),
            "pending": list(_pending),
            "exemplars": {k: dict(v) for k, v in _exemplars.items()},
        }


def restore_state(snapshot: dict) -> None:
    global ENABLED, SHIPPING
    with _lock:
        ENABLED = snapshot.get("enabled", True)
        SHIPPING = snapshot.get("shipping", False)
        _traces.clear()
        for k, v in snapshot.get("traces", {}).items():
            _traces[k] = [list(v[0]), v[1], v[2]]
        _finished.clear()
        _finished.extend(dict(e) for e in snapshot.get("finished", []))
        _finished_routes.clear()
        _finished_routes.update(snapshot.get("finished_routes", {}))
        _pending.clear()
        _pending.extend(snapshot.get("pending", []))
        _exemplars.clear()
        for k, v in snapshot.get("exemplars", {}).items():
            _exemplars[k] = dict(v)
        _raw.clear()
        _dist_cache.clear()


def reset() -> None:
    restore_state({"enabled": True})
