"""Unique identifiers for tasks, actors, objects, nodes, jobs and placement groups.

Mirrors the role of the reference's ID layer (``src/ray/common/id.h``): every
entity in the system is addressed by a fixed-size binary ID. Like the
reference, an ObjectID embeds provenance (the task that created it plus a
return/put index) so ownership and lineage can be derived from the ID itself.
The representation here is deliberately simpler: flat 16/8-byte random IDs
with a structured ObjectID, rather than the reference's nested Job/Actor/Task
bit-packing.
"""

from __future__ import annotations

import os
import threading

_UNIQUE_SIZE = 16


class _RandomPool:
    """Batched entropy: one os.urandom syscall per 4096 ids instead of
    one per id (id generation showed up in the submit-path profile at
    fan-out rates; the reference generates ids from a per-process PRNG
    for the same reason)."""

    __slots__ = ("_buf", "_pos", "_lock")
    _CHUNK = 4096 * _UNIQUE_SIZE

    def __init__(self):
        self._buf = b""
        self._pos = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._pos + n > len(self._buf):
                self._buf = os.urandom(self._CHUNK)
                self._pos = 0
            out = self._buf[self._pos:self._pos + n]
            self._pos += n
            return out


_random_pool = _RandomPool()


class BaseID:
    """A fixed-size immutable binary identifier."""

    __slots__ = ("_binary", "_hash")
    SIZE = _UNIQUE_SIZE

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_random_pool.take(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other) -> bool:
        return self._binary < other._binary

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class ObjectID(BaseID):
    """TaskID (16B) + 4-byte big-endian index.

    Index 0..2**31 are task returns; indices with the top bit set are
    ``put`` objects, mirroring the provenance encoding of the reference's
    ObjectID (owner task + index) without its bit-level layout.
    """

    SIZE = _UNIQUE_SIZE + 4
    _PUT_BIT = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + (cls._PUT_BIT | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:_UNIQUE_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._binary[_UNIQUE_SIZE:], "big") & ~self._PUT_BIT

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._binary[_UNIQUE_SIZE:], "big") & self._PUT_BIT)


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
