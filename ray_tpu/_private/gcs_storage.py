"""Pluggable GCS table storage: in-memory by default, SQLite for
persistence across head restarts.

Role-equivalent to the reference's GCS store clients —
`src/ray/gcs/store_client/in_memory_store_client.h:31` (default) and
`redis_store_client.h:28` (the fault-tolerance backend) behind the
`GcsTableStorage` facade (`gcs_server/gcs_table_storage.h`). SQLite plays
Redis's durability role here: single-file, transactional, in the standard
library — the right "external store" for a single-head deployment (a real
Redis client would drop in behind the same ABC).

Select via ``ray_tpu.init(_system_config={"gcs_storage_path": ...})`` or
the ``RAY_TPU_GCS_STORAGE_PATH`` env var; empty means in-memory.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks


class StoreClient:
    """Typed-table KV: (table, key) -> bytes."""

    def put(self, table: str, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def get_all(self, table: str) -> List[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def delete(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        raise NotImplementedError

    def flush(self) -> None:
        """Make every accepted write durable (no-op for in-memory)."""

    def close(self) -> None:
        # API contract (raylint R4): teardown makes accepted writes
        # durable. Backends overriding close() must keep that promise.
        self.flush()

    def crash(self) -> None:
        """Simulated hard process death: release resources WITHOUT the
        durability promise of close() — writes still riding the
        group-commit window are deliberately lost (the crash-mode head
        failover's documented loss bound)."""
        self.close()


class InMemoryStoreClient(StoreClient):
    """Reference: `in_memory_store_client.h:31`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[bytes, bytes]] = {}

    def _table(self, table: str) -> Dict[bytes, bytes]:
        t = self._tables.get(table)
        if t is None:
            t = self._tables[table] = {}
        return t

    def put(self, table: str, key: bytes, value: bytes) -> None:
        with self._lock:
            self._table(table)[key] = value

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._table(table).get(key)

    def get_all(self, table: str) -> List[Tuple[bytes, bytes]]:
        with self._lock:
            return list(self._table(table).items())

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            self._table(table).pop(key, None)

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._table(table) if k.startswith(prefix)]


class SqliteStoreClient(StoreClient):
    """Durable backend (the reference's Redis role,
    `redis_store_client.h:28`): state survives head-process restarts.

    Writes are GROUP-COMMITTED: each put/delete executes immediately
    (reads on this connection see it at once) but the fsync-bearing
    COMMIT is deferred to a flusher thread that batches everything
    accumulated within ``gcs_commit_interval_s`` into one transaction —
    the reference's async GCS-storage write path. A registry write burst
    (actor churn, KV traffic) costs one disk transaction per window
    instead of one per write. ``flush()`` forces durability; graceful
    teardown paths (worker shutdown, head failover handoff) call it.
    Set the interval to 0 for synchronous per-write commits.
    """

    def __init__(self, path: str, commit_interval_s: Optional[float] = None):
        import sqlite3

        if commit_interval_s is None:
            from ray_tpu._private.config import ray_config

            commit_interval_s = ray_config.gcs_commit_interval_s
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " tbl TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))")
        # WAL: concurrent readers during writes, and a crash mid-write
        # never corrupts committed state.
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.commit()
        from ray_tpu._private import perf_stats

        self._stat_writes = perf_stats.counter("gcs_writes")
        # Per-store commit accounting: the multi-process head reads
        # these off each shard's own store (shard_stats ->
        # ray_tpu_head_shard_commit_seconds) so per-shard group-commit
        # latency — the shard's durability loss bound in time units —
        # is observable without guessing from the global latency stat.
        self.commit_count = 0
        self.commit_seconds_total = 0.0
        self.last_commit_s = 0.0
        self._interval = max(0.0, float(commit_interval_s or 0.0))
        self._dirty = threading.Event()
        self._closed = threading.Event()
        self._flusher = None
        if self._interval > 0:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True, name="gcs-commit")
            self._flusher.start()

    def _mark_dirty_locked(self) -> None:
        self._stat_writes.inc()
        if self._interval > 0:
            self._dirty.set()
        else:
            self._conn.commit()

    def put(self, table: str, key: bytes, value: bytes) -> None:
        # Yield point BEFORE the lock: the accept-vs-commit ordering is
        # the group-commit protocol's racy surface (a write accepted in
        # the window rides the next COMMIT; raymc's durability check
        # explores every placement of this accept against the commit
        # and against an injected crash).
        sanitize_hooks.sched_point("gcs.put")
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (tbl, key, value) VALUES (?, ?, ?)"
                " ON CONFLICT(tbl, key) DO UPDATE SET value=excluded.value",
                (table, key, value))
            self._mark_dirty_locked()

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE tbl=? AND key=?",
                (table, key)).fetchone()
        return row[0] if row else None

    def get_all(self, table: str) -> List[Tuple[bytes, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE tbl=?", (table,)).fetchall()
        return [(bytes(k), bytes(v)) for k, v in rows]

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE tbl=? AND key=?",
                               (table, key))
            self._mark_dirty_locked()

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        return [k for k, _ in self.get_all(table) if k.startswith(prefix)]

    def _flush_loop(self) -> None:
        while not self._closed.is_set():
            self._dirty.wait()
            if self._closed.is_set():
                return
            # Group-commit window: let the burst accumulate, then one
            # transaction covers all of it. The window waits on the
            # CLOSED event, not a bare sleep — close() commits pending
            # writes itself, and a flusher stuck in a long window
            # outlives its store otherwise (a 300s test interval held
            # the thread for 300s after close).
            if self._closed.wait(self._interval):
                return
            self.flush()

    def flush(self) -> None:
        from ray_tpu._private import perf_stats

        t0 = time.monotonic()
        with self._lock:
            # Crash-fault seams, UNDER the write lock so the kill
            # boundary is exact: death at `before` loses everything the
            # pending transaction accumulated (WAL rolls it back);
            # death at `after` is post-COMMIT — those writes must
            # survive restart even though this flush() never returned.
            # No concurrent put can interleave between the commit and
            # the `after` point (both sit inside one lock hold).
            sanitize_hooks.crash_point("gcs.commit.before")
            try:
                self._conn.commit()
            except Exception:
                # Commit failed (disk full, I/O error, closing): KEEP
                # the dirty flag so the flusher retries next window —
                # clearing it here would silently drop accepted writes.
                if not self._closed.is_set() and \
                        not getattr(self, "_commit_err_logged", False):
                    self._commit_err_logged = True  # once, not per retry
                    import logging

                    logging.getLogger(__name__).warning(
                        "GCS group commit failed; will retry",
                        exc_info=True)
                return
            sanitize_hooks.crash_point("gcs.commit.after")
            self._commit_err_logged = False
            self._dirty.clear()
            self.commit_count += 1
            self.last_commit_s = time.monotonic() - t0
            self.commit_seconds_total += self.last_commit_s
        perf_stats.latency("gcs_commit_seconds").record(
            time.monotonic() - t0)

    def close(self) -> None:
        self._closed.set()
        self._dirty.set()  # unblock the flusher
        with self._lock:
            try:
                self._conn.commit()
            finally:
                self._conn.close()

    def crash(self) -> None:
        """Hard-death teardown: drop the connection with the pending
        transaction UNCOMMITTED (sqlite rolls it back) — exactly what a
        SIGKILL'd process leaves behind. Acked (flushed) writes are on
        disk; the open group-commit window is lost. Under the store
        lock so a mid-statement writer is sequenced before the close
        (closing under a running conn.execute is a C-level
        use-after-free)."""
        self._closed.set()
        self._dirty.set()
        with self._lock:
            try:
                self._conn.rollback()
            except Exception:
                pass
            try:
                self._conn.close()
            except Exception:
                pass


def make_store_client() -> StoreClient:
    """Backend selection from the config table."""
    from ray_tpu._private.config import ray_config

    path = getattr(ray_config, "gcs_storage_path", "")
    if path:
        return SqliteStoreClient(path)
    return InMemoryStoreClient()
